"""Image transforms (reference: python/paddle/vision/transforms/ —
numpy-array implementations of the torchvision-style transform set)."""

from __future__ import annotations

import numbers
import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor:
    """HWC uint8 -> CHW float32/255 (no-op on already-CHW float)."""

    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format
        self.keys = keys

    def __call__(self, img):
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[None]
        elif img.ndim == 3 and img.shape[-1] in (1, 3, 4) and \
                img.shape[0] not in (1, 3, 4):
            img = np.transpose(img, (2, 0, 1))
        if img.dtype == np.uint8:
            img = img.astype(np.float32) / 255.0
        out = img.astype(np.float32)
        if getattr(self, "data_format", "CHW") == "HWC":
            out = np.transpose(out, (1, 2, 0))
        return out


class Normalize:
    def __init__(self, mean, std, data_format="CHW", to_rgb=False,
                 keys=None):
        self.keys = keys
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, dtype=np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (img - self.mean.reshape(shape)) / self.std.reshape(shape)


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.keys = keys
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        import jax
        import jax.numpy as jnp
        img = np.asarray(img)
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        shape = list(img.shape)
        shape[h_ax], shape[w_ax] = self.size
        return np.asarray(jax.image.resize(jnp.asarray(img), shape,
                                           method="linear"))


class CenterCrop:
    def __init__(self, size, keys=None):
        self.keys = keys
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        img = np.asarray(img)
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        th, tw = self.size
        i = max((img.shape[h_ax] - th) // 2, 0)
        j = max((img.shape[w_ax] - tw) // 2, 0)
        sl = [slice(None)] * img.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return img[tuple(sl)]


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode
        self.keys = keys

    def __call__(self, img):
        img = np.asarray(img)
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        mode = {"constant": "constant", "edge": "edge",
                "reflect": "reflect", "symmetric": "symmetric"}[
            self.padding_mode]
        kw = {"constant_values": self.fill} if mode == "constant" else {}
        if self.padding:
            pad = [(0, 0)] * img.ndim
            pad[h_ax] = (self.padding, self.padding)
            pad[w_ax] = (self.padding, self.padding)
            img = np.pad(img, pad, mode=mode, **kw)
        th, tw = self.size
        if self.pad_if_needed and (img.shape[h_ax] < th or
                                   img.shape[w_ax] < tw):
            pad = [(0, 0)] * img.ndim
            pad[h_ax] = (0, max(0, th - img.shape[h_ax]))
            pad[w_ax] = (0, max(0, tw - img.shape[w_ax]))
            img = np.pad(img, pad, mode=mode, **kw)
        i = np.random.randint(0, img.shape[h_ax] - th + 1)
        j = np.random.randint(0, img.shape[w_ax] - tw + 1)
        sl = [slice(None)] * img.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return img[tuple(sl)]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob
        self.keys = keys

    def __call__(self, img):
        img = np.asarray(img)
        if np.random.random() < self.prob:
            chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
            return np.flip(img, axis=2 if chw else 1).copy()
        return img


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob
        self.keys = keys

    def __call__(self, img):
        img = np.asarray(img)
        if np.random.random() < self.prob:
            chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
            return np.flip(img, axis=1 if chw else 0).copy()
        return img


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order
        self.keys = keys

    def __call__(self, img):
        return np.transpose(np.asarray(img), self.order)


# ---------------------------------------------------------------------------
# functional API (reference: python/paddle/vision/transforms/functional.py;
# numpy HWC images, uint8 or float; CHW tolerated where axes are detectable)
# ---------------------------------------------------------------------------

def _axes(img):
    chw = img.ndim == 3 and img.shape[0] in (1, 3, 4) and \
        img.shape[-1] not in (1, 3, 4)
    return ((1, 2), 0) if chw else ((0, 1), (2 if img.ndim == 3 else None))


def to_tensor(pic, data_format="CHW"):
    """reference: F.to_tensor — HWC uint8 -> float32/255 in CHW."""
    out = ToTensor()(pic)
    if data_format == "HWC":
        out = np.transpose(out, (1, 2, 0))
    return out


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    """reference: F.normalize."""
    return Normalize(mean, std, data_format)(img)


def hflip(img):
    """reference: F.hflip."""
    img = np.asarray(img)
    (h_ax, w_ax), _ = _axes(img)
    return np.flip(img, axis=w_ax).copy()


def vflip(img):
    """reference: F.vflip."""
    img = np.asarray(img)
    (h_ax, w_ax), _ = _axes(img)
    return np.flip(img, axis=h_ax).copy()


def resize(img, size, interpolation="bilinear"):
    """reference: F.resize; int size scales the short edge."""
    img = np.asarray(img)
    (h_ax, w_ax), _ = _axes(img)
    if isinstance(size, numbers.Number):
        h, w = img.shape[h_ax], img.shape[w_ax]
        short, long = (h, w) if h < w else (w, h)
        ns = int(size)
        nl = int(round(long * ns / short))
        size = (ns, nl) if h < w else (nl, ns)
    return Resize(tuple(size), interpolation)(img)


def crop(img, top, left, height, width):
    """reference: F.crop."""
    img = np.asarray(img)
    (h_ax, w_ax), _ = _axes(img)
    sl = [slice(None)] * img.ndim
    sl[h_ax] = slice(top, top + height)
    sl[w_ax] = slice(left, left + width)
    return img[tuple(sl)]


def center_crop(img, output_size):
    """reference: F.center_crop."""
    return CenterCrop(output_size)(img)


def pad(img, padding, fill=0, padding_mode="constant"):
    """reference: F.pad; padding int or (l, t) or (l, t, r, b)."""
    img = np.asarray(img)
    (h_ax, w_ax), _ = _axes(img)
    if isinstance(padding, numbers.Number):
        pl = pt_ = pr = pb = int(padding)
    elif len(padding) == 2:
        pl, pt_ = padding
        pr, pb = padding
    else:
        pl, pt_, pr, pb = padding
    spec = [(0, 0)] * img.ndim
    spec[h_ax] = (pt_, pb)
    spec[w_ax] = (pl, pr)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(img, spec, mode=mode, **kw)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """reference: F.rotate — counter-clockwise degrees, inverse-map
    sampling (nearest or bilinear)."""
    img = np.asarray(img)
    (h_ax, w_ax), c_ax = _axes(img)
    hwc = img if c_ax != 0 else np.transpose(img, (1, 2, 0))
    if hwc.ndim == 2:
        hwc = hwc[:, :, None]
        squeeze = True
    else:
        squeeze = False
    h, w = hwc.shape[0], hwc.shape[1]
    # positive angle = counter-clockwise (PIL convention); the image
    # y-axis points down, so negate the angle for the math-convention
    # rotation below
    theta = -np.deg2rad(angle)
    cos, sin = np.cos(theta), np.sin(theta)
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None else \
        (center[1], center[0])
    if expand:
        # round before ceil: cos(90deg) is ~6e-17, not 0, and the epsilon
        # must not bump the size by one
        nh = int(np.ceil(np.round(abs(h * cos) + abs(w * sin), 6)))
        nw = int(np.ceil(np.round(abs(w * cos) + abs(h * sin), 6)))
        ocy, ocx = (nh - 1) / 2.0, (nw - 1) / 2.0
    else:
        # rotate about the pivot: src = R^-1(out - c) + c, so the
        # outgoing offset must use the same pivot as the incoming one
        nh, nw = h, w
        ocy, ocx = cy, cx
    yy, xx = np.meshgrid(np.arange(nh), np.arange(nw), indexing="ij")
    # inverse rotation: output pixel -> source coordinate
    ys = (yy - ocy) * cos - (xx - ocx) * sin + cy
    xs = (yy - ocy) * sin + (xx - ocx) * cos + cx
    if interpolation == "nearest":
        yi = np.round(ys).astype(np.int64)
        xi = np.round(xs).astype(np.int64)
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        out = np.full((nh, nw, hwc.shape[2]), fill, dtype=hwc.dtype)
        out[valid] = hwc[yi[valid], xi[valid]]
    else:  # bilinear
        y0 = np.floor(ys).astype(np.int64)
        x0 = np.floor(xs).astype(np.int64)
        wy = (ys - y0)[..., None]
        wx = (xs - x0)[..., None]
        acc = np.zeros((nh, nw, hwc.shape[2]), np.float32)
        wsum = np.zeros((nh, nw, 1), np.float32)
        for dy, dx, wgt in ((0, 0, (1 - wy) * (1 - wx)),
                            (0, 1, (1 - wy) * wx),
                            (1, 0, wy * (1 - wx)),
                            (1, 1, wy * wx)):
            yi, xi = y0 + dy, x0 + dx
            valid = ((yi >= 0) & (yi < h) & (xi >= 0) & (xi < w))[..., None]
            yc, xc = np.clip(yi, 0, h - 1), np.clip(xi, 0, w - 1)
            acc += np.where(valid, wgt * hwc[yc, xc].astype(np.float32), 0)
            wsum += np.where(valid, wgt, 0)
        out = np.where(wsum > 0, acc / np.maximum(wsum, 1e-8), fill)
        out = out.astype(hwc.dtype)
    if squeeze:
        out = out[:, :, 0]
    if c_ax == 0 and out.ndim == 3:
        out = np.transpose(out, (2, 0, 1))
    return out


def to_grayscale(img, num_output_channels=1):
    """reference: F.to_grayscale (ITU-R 601-2 luma)."""
    img = np.asarray(img)
    (h_ax, w_ax), c_ax = _axes(img)
    if img.ndim == 2:
        g = img.astype(np.float32)
    else:
        hwc = img if c_ax != 0 else np.transpose(img, (1, 2, 0))
        if hwc.shape[2] == 1:
            g = hwc[:, :, 0].astype(np.float32)
        else:
            g = (0.299 * hwc[..., 0] + 0.587 * hwc[..., 1] +
                 0.114 * hwc[..., 2]).astype(np.float32)
    g = g.astype(img.dtype) if img.dtype == np.uint8 else g
    out = np.repeat(g[:, :, None], num_output_channels, axis=2)
    if c_ax == 0 and img.ndim == 3:
        out = np.transpose(out, (2, 0, 1))
    return out


def _blend(a, b, factor, dtype):
    out = factor * a.astype(np.float32) + (1 - factor) * b
    if np.issubdtype(np.dtype(dtype), np.integer):
        out = np.clip(out, 0, 255)
    return out.astype(dtype)


def adjust_brightness(img, brightness_factor):
    """reference: F.adjust_brightness — blend with black."""
    img = np.asarray(img)
    return _blend(img, 0.0, brightness_factor, img.dtype)


def adjust_contrast(img, contrast_factor):
    """reference: F.adjust_contrast — blend with the grayscale mean."""
    img = np.asarray(img)
    mean = to_grayscale(img).astype(np.float32).mean()
    return _blend(img, mean, contrast_factor, img.dtype)


def adjust_saturation(img, saturation_factor):
    """reference: F.adjust_saturation — blend with grayscale."""
    img = np.asarray(img)
    (h_ax, w_ax), c_ax = _axes(img)
    gray = to_grayscale(img, 3 if img.ndim == 3 else 1)
    return _blend(img, gray.astype(np.float32), saturation_factor,
                  img.dtype)


def adjust_hue(img, hue_factor):
    """reference: F.adjust_hue — shift hue in HSV space;
    hue_factor in [-0.5, 0.5]."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    img = np.asarray(img)
    (h_ax, w_ax), c_ax = _axes(img)
    hwc = img if c_ax != 0 else np.transpose(img, (1, 2, 0))
    scale = 255.0 if img.dtype == np.uint8 else 1.0
    rgb = hwc.astype(np.float32) / scale
    mx = rgb.max(-1)
    mn = rgb.min(-1)
    diff = mx - mn + 1e-12
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    hch = np.where(mx == r, ((g - b) / diff) % 6,
                   np.where(mx == g, (b - r) / diff + 2,
                            (r - g) / diff + 4)) / 6.0
    s = np.where(mx > 0, diff / (mx + 1e-12), 0.0)
    v = mx
    hch = (hch + hue_factor) % 1.0
    i = np.floor(hch * 6.0)
    f = hch * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(np.int32) % 6
    r2 = np.choose(i, [v, q, p, p, t, v])
    g2 = np.choose(i, [t, v, v, q, p, p])
    b2 = np.choose(i, [p, p, t, v, v, q])
    out = np.stack([r2, g2, b2], axis=-1) * scale
    if img.dtype == np.uint8:
        out = np.clip(np.round(out), 0, 255)
    out = out.astype(img.dtype)
    if c_ax == 0:
        out = np.transpose(out, (2, 0, 1))
    return out


# ---------------------------------------------------------------------------
# transform classes over the functional API
# ---------------------------------------------------------------------------

class BaseTransform:
    """reference: paddle.vision.transforms.BaseTransform — subclasses
    implement _apply_image (and optionally _apply_{coords,boxes,mask});
    __call__ routes plain images through _apply_image."""

    def __init__(self, keys=None):
        self.keys = keys

    def _apply_image(self, img):
        raise NotImplementedError

    def __call__(self, inputs):
        if self.keys is None or isinstance(inputs, np.ndarray):
            return self._apply_image(np.asarray(inputs))
        outs = []
        for key, data in zip(self.keys, inputs):
            fn = getattr(self, f"_apply_{key}", None)
            outs.append(fn(data) if fn else data)
        return tuple(outs)


class BrightnessTransform(BaseTransform):
    """reference: BrightnessTransform(value) — random factor in
    [max(0, 1-value), 1+value]."""

    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("brightness value must be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("contrast value must be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("saturation value must be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(-self.value, self.value)
        return adjust_hue(img, f)


class ColorJitter(BaseTransform):
    """reference: ColorJitter(brightness, contrast, saturation, hue) —
    applies the four jitters in random order."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        order = np.random.permutation(len(self.transforms))
        for i in order:
            img = self.transforms[i]._apply_image(img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class RandomResizedCrop(BaseTransform):
    """reference: RandomResizedCrop(size, scale, ratio) — random area +
    aspect crop, resized to size."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = np.asarray(img)
        (h_ax, w_ax), _ = _axes(img)
        h, w = img.shape[h_ax], img.shape[w_ax]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            log_r = np.log(np.asarray(self.ratio))
            ar = np.exp(np.random.uniform(log_r[0], log_r[1]))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = np.random.randint(0, h - ch + 1)
                left = np.random.randint(0, w - cw + 1)
                patch = crop(img, top, left, ch, cw)
                return Resize(self.size, self.interpolation)(patch)
        return Resize(self.size, self.interpolation)(
            CenterCrop(min(h, w))(img))


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, self.expand,
                      self.center, self.fill)
