"""paddle.vision.ops (reference: python/paddle/vision/ops.py __all__:
yolo_loss, yolo_box, deform_conv2d, DeformConv2D, read_file, decode_jpeg).

The compute kernels live in paddle_tpu.ops (yolov3_loss/yolo_box/
deformable_conv); this module provides the reference's argument order on
top of them plus the file/JPEG IO helpers.
"""

from __future__ import annotations

import numpy as np

from .. import dispatch
from ..nn.conv import DeformConv2D
from ..tensor import Tensor

F = dispatch.wrapped_ops

__all__ = ["yolo_loss", "yolo_box", "deform_conv2d", "DeformConv2D",
           "read_file", "decode_jpeg"]


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """reference: paddle.vision.ops.yolo_loss (yolov3_loss_op.cc)."""
    return F["yolov3_loss"](x, gt_box, gt_label, anchors, anchor_mask,
                            class_num, ignore_thresh, downsample_ratio,
                            gt_score, use_label_smooth)


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """reference: paddle.vision.ops.yolo_box (yolo_box_op.cc)."""
    return F["yolo_box"](x, img_size, anchors, class_num, conf_thresh,
                         downsample_ratio, clip_bbox, scale_x_y,
                         iou_aware, iou_aware_factor)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """reference: paddle.vision.ops.deform_conv2d (v1 without mask, v2
    with)."""
    return F["deformable_conv"](x, offset, weight, mask, bias, stride,
                                padding, dilation, deformable_groups,
                                groups)


def read_file(filename: str, name=None) -> Tensor:
    """reference: paddle.vision.ops.read_file — raw bytes as a uint8
    tensor."""
    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(np.frombuffer(data, dtype=np.uint8))


def decode_jpeg(x, mode: str = "unchanged", name=None) -> Tensor:
    """reference: paddle.vision.ops.decode_jpeg (nvjpeg-backed there) —
    decodes a uint8 byte tensor to CHW uint8 via the host image backend
    (PIL)."""
    import io

    from PIL import Image

    raw = bytes(np.asarray(x.value if isinstance(x, Tensor) else x,
                           dtype=np.uint8))
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = np.transpose(arr, (2, 0, 1))
    return Tensor(arr.copy())
