"""Built-in datasets (reference: python/paddle/vision/datasets/ — MNIST,
FashionMNIST, Cifar10/100, Flowers, VOC2012).

This environment has zero egress, so each dataset reads from a local
``data_file`` when given and otherwise serves a deterministic synthetic
sample set with the real shapes/dtypes — enough for tests, smoke training,
and benchmarks (the reference's tests likewise run tiny subsets).
"""

from __future__ import annotations

import gzip
import os
import struct
import threading
from typing import Callable, Optional

import numpy as np

from ..io.dataset import Dataset


class _TarReader:
    """Thread- and process-worker-safe random access into a tar archive:
    reads are serialized under a lock (TarFile seeks on ONE file object),
    and pickling drops the handle and reopens lazily in the worker (a
    TarFile itself is unpicklable)."""

    def __init__(self, path: str):
        self.path = path
        self._open()

    def _open(self):
        import tarfile
        self._lock = threading.Lock()
        self._tar = tarfile.open(self.path)
        self.members = {m.name: m for m in self._tar.getmembers()}

    def read(self, name: str) -> bytes:
        with self._lock:
            return self._tar.extractfile(self.members[name]).read()

    def __getstate__(self):
        return {"path": self.path}

    def __setstate__(self, state):
        self.path = state["path"]
        self._open()


class MNIST(Dataset):
    def __init__(self, image_path: Optional[str] = None,
                 label_path: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = True,
                 backend: str = "cv2", synthetic_size: Optional[int] = None):
        self.mode = mode
        self.transform = transform
        if image_path and os.path.exists(image_path):
            self.images, self.labels = self._load_idx(image_path, label_path)
        else:
            n = synthetic_size or (600 if mode == "train" else 100)
            rng = np.random.default_rng(0 if mode == "train" else 1)
            self.labels = rng.integers(0, 10, n).astype(np.int64)
            base = rng.normal(0.1307, 0.3081, (n, 28, 28)).astype(np.float32)
            # encode the label coarsely in the image so training can learn
            for i, lbl in enumerate(self.labels):
                base[i, :3, int(lbl) * 2:int(lbl) * 2 + 2] += 2.0
            self.images = base

    @staticmethod
    def _load_idx(image_path, label_path):
        opener = gzip.open if image_path.endswith(".gz") else open
        with opener(image_path, "rb") as f:
            _, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), dtype=np.uint8).reshape(
                n, rows, cols).astype(np.float32) / 255.0
        with opener(label_path, "rb") as f:
            _, n = struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), dtype=np.uint8).astype(np.int64)
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx][np.newaxis]  # [1, 28, 28]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = True,
                 backend: str = "cv2", synthetic_size: Optional[int] = None):
        self.mode = mode
        self.transform = transform
        self.num_classes = 10
        n = synthetic_size or (500 if mode == "train" else 100)
        rng = np.random.default_rng(2 if mode == "train" else 3)
        self.labels = rng.integers(0, self.num_classes, n).astype(np.int64)
        self.images = rng.normal(0.5, 0.25, (n, 3, 32, 32)).astype(
            np.float32)
        for i, lbl in enumerate(self.labels):
            self.images[i, 0, :2, int(lbl) * 3:int(lbl) * 3 + 3] += 1.5

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.num_classes = 100


class FakeImageNet(Dataset):
    """Synthetic ImageNet-shaped dataset for ResNet-50 benchmarks."""

    def __init__(self, size: int = 1024, image_shape=(3, 224, 224),
                 num_classes: int = 1000, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self._seed = seed

    def __getitem__(self, idx):
        rng = np.random.default_rng(self._seed + idx)
        img = rng.standard_normal(self.image_shape).astype(np.float32)
        label = np.int64(idx % self.num_classes)
        return img, label

    def __len__(self):
        return self.size


_IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm",
                   ".tif", ".tiff", ".webp")


class DatasetFolder(Dataset):
    """Directory-per-class image dataset (reference:
    python/paddle/vision/datasets/folder.py DatasetFolder):
    root/class_x/xxx.png layout; samples are (image, class_index)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        extensions = extensions or _IMG_EXTENSIONS
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class folders under {root!r}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fname in sorted(files):
                    path = os.path.join(dirpath, fname)
                    ok = (is_valid_file(path) if is_valid_file else
                          fname.lower().endswith(tuple(extensions)))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no image files under {root!r}")

    @staticmethod
    def _default_loader(path):
        from . import image_load
        return np.asarray(image_load(path))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat image dataset without labels (reference: vision/datasets/
    folder.py ImageFolder): every image under root; samples are
    [image]."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or DatasetFolder._default_loader
        extensions = extensions or _IMG_EXTENSIONS
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(dirpath, fname)
                ok = (is_valid_file(path) if is_valid_file else
                      fname.lower().endswith(tuple(extensions)))
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise RuntimeError(f"no image files under {root!r}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Flowers-102 (reference: vision/datasets/flowers.py). Given the
    official archives — ``data_file`` 102flowers.tgz (jpg/image_%05d.jpg
    members), ``label_file`` imagelabels.mat ('labels', 1-indexed),
    ``setid_file`` setid.mat ('trnid'/'valid'/'tstid' image indices) —
    parses the real formats (scipy.io + PIL decode). Otherwise serves a
    deterministic synthetic set with the real shapes (zero-egress
    environment — see module docstring).

    Mirrors the reference's split swap (flowers.py MODE_FLAG_MAP):
    'train' reads the (larger) tstid list, 'test' reads trnid."""

    _SPLIT_SIZES = {"train": 60, "valid": 20, "test": 60}
    _MODE_FLAG = {"train": "tstid", "test": "trnid", "valid": "valid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        self._tar = None
        if data_file and os.path.exists(data_file):
            if not (label_file and os.path.exists(label_file) and
                    setid_file and os.path.exists(setid_file)):
                raise ValueError(
                    "Flowers needs label_file (imagelabels.mat) and "
                    "setid_file (setid.mat) together with data_file")
            import scipy.io as scio
            self._labels_mat = scio.loadmat(label_file)["labels"][0]
            self._indexes = scio.loadmat(setid_file)[
                self._MODE_FLAG.get(mode.lower(), "valid")][0]
            self._tar = _TarReader(data_file)
            return
        n = self._SPLIT_SIZES.get(mode, 60)
        # per-mode seeds: splits must be disjoint image sets
        rng = np.random.RandomState(
            102 + {"train": 0, "valid": 1, "test": 2}.get(mode, 3))
        self._images = (rng.rand(n, 64, 64, 3) * 255).astype("uint8")
        # labels shaped [1] like the real-archive path (reference
        # flowers.py:127 returns np.array([label]))
        self._labels = (rng.randint(0, 102, size=(n, 1))).astype("int64")

    def __getitem__(self, idx):
        if self._tar is not None:
            import io as _io

            from PIL import Image
            index = int(self._indexes[idx])
            name = "jpg/image_%05d.jpg" % index
            img = np.asarray(Image.open(_io.BytesIO(self._tar.read(name))))
            label = np.array([self._labels_mat[index - 1]], "int64")
            if self.transform is not None:
                img = self.transform(img)
            return img, label
        img = self._images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self._labels[idx]

    def __len__(self):
        if self._tar is not None:
            return len(self._indexes)
        return len(self._images)


class VOC2012(Dataset):
    """Pascal VOC 2012 segmentation (reference: vision/datasets/voc2012.py):
    samples are (image, segmentation mask). Given the official
    VOCtrainval tar via ``data_file``, parses the real layout
    (ImageSets/Segmentation/{mode}.txt -> JPEGImages/*.jpg +
    SegmentationClass/*.png, PIL-decoded). A directory of (img, mask)
    .npy pairs also works; otherwise serves deterministic synthetic
    pairs with real shapes/dtypes (zero-egress environment)."""

    _SET = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
    _IMG = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
    _MASK = "VOCdevkit/VOC2012/SegmentationClass/{}.png"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        self._tar = None
        if data_file and os.path.isfile(data_file):
            self._tar = _TarReader(data_file)
            # reference MODE_FLAG_MAP (vision/datasets/voc2012.py:37):
            # train -> trainval, test -> train, valid -> val
            flag = {"train": "trainval", "valid": "val",
                    "test": "train"}.get(mode, "trainval")
            listing = self._tar.read(self._SET.format(flag))
            self._names = [ln.strip().decode()
                           for ln in listing.splitlines() if ln.strip()]
            self._pairs = None
        elif data_file and os.path.isdir(data_file):
            files = sorted(f for f in os.listdir(data_file)
                           if f.endswith("_img.npy"))
            self._pairs = [
                (np.load(os.path.join(data_file, f)),
                 np.load(os.path.join(data_file,
                                      f.replace("_img", "_mask"))))
                for f in files]
        else:
            n = {"train": 24, "valid": 8, "test": 8}.get(mode, 8)
            rng = np.random.RandomState(2012)
            self._pairs = [((rng.rand(96, 96, 3) * 255).astype("uint8"),
                            rng.randint(0, 21, size=(96, 96)).astype(
                                "int64")) for _ in range(n)]

    def __getitem__(self, idx):
        if self._tar is not None:
            import io as _io

            from PIL import Image
            name = self._names[idx]
            img = np.asarray(Image.open(_io.BytesIO(
                self._tar.read(self._IMG.format(name)))))
            mask = np.asarray(Image.open(_io.BytesIO(
                self._tar.read(self._MASK.format(name)))), dtype="int64")
        else:
            img, mask = self._pairs[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        if self._tar is not None:
            return len(self._names)
        return len(self._pairs)
