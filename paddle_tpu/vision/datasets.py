"""Built-in datasets (reference: python/paddle/vision/datasets/ — MNIST,
FashionMNIST, Cifar10/100, Flowers, VOC2012).

This environment has zero egress, so each dataset reads from a local
``data_file`` when given and otherwise serves a deterministic synthetic
sample set with the real shapes/dtypes — enough for tests, smoke training,
and benchmarks (the reference's tests likewise run tiny subsets).
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Callable, Optional

import numpy as np

from ..io.dataset import Dataset


class MNIST(Dataset):
    def __init__(self, image_path: Optional[str] = None,
                 label_path: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = True,
                 backend: str = "cv2", synthetic_size: Optional[int] = None):
        self.mode = mode
        self.transform = transform
        if image_path and os.path.exists(image_path):
            self.images, self.labels = self._load_idx(image_path, label_path)
        else:
            n = synthetic_size or (600 if mode == "train" else 100)
            rng = np.random.default_rng(0 if mode == "train" else 1)
            self.labels = rng.integers(0, 10, n).astype(np.int64)
            base = rng.normal(0.1307, 0.3081, (n, 28, 28)).astype(np.float32)
            # encode the label coarsely in the image so training can learn
            for i, lbl in enumerate(self.labels):
                base[i, :3, int(lbl) * 2:int(lbl) * 2 + 2] += 2.0
            self.images = base

    @staticmethod
    def _load_idx(image_path, label_path):
        opener = gzip.open if image_path.endswith(".gz") else open
        with opener(image_path, "rb") as f:
            _, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), dtype=np.uint8).reshape(
                n, rows, cols).astype(np.float32) / 255.0
        with opener(label_path, "rb") as f:
            _, n = struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), dtype=np.uint8).astype(np.int64)
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx][np.newaxis]  # [1, 28, 28]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = True,
                 backend: str = "cv2", synthetic_size: Optional[int] = None):
        self.mode = mode
        self.transform = transform
        self.num_classes = 10
        n = synthetic_size or (500 if mode == "train" else 100)
        rng = np.random.default_rng(2 if mode == "train" else 3)
        self.labels = rng.integers(0, self.num_classes, n).astype(np.int64)
        self.images = rng.normal(0.5, 0.25, (n, 3, 32, 32)).astype(
            np.float32)
        for i, lbl in enumerate(self.labels):
            self.images[i, 0, :2, int(lbl) * 3:int(lbl) * 3 + 3] += 1.5

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.num_classes = 100


class FakeImageNet(Dataset):
    """Synthetic ImageNet-shaped dataset for ResNet-50 benchmarks."""

    def __init__(self, size: int = 1024, image_shape=(3, 224, 224),
                 num_classes: int = 1000, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self._seed = seed

    def __getitem__(self, idx):
        rng = np.random.default_rng(self._seed + idx)
        img = rng.standard_normal(self.image_shape).astype(np.float32)
        label = np.int64(idx % self.num_classes)
        return img, label

    def __len__(self):
        return self.size
