"""paddle_tpu.vision (reference parity: python/paddle/vision/)."""

from . import datasets, models, ops, transforms

_image_backend = "pil"


def set_image_backend(backend: str) -> None:
    """reference: paddle.vision.set_image_backend ('pil' or 'cv2')."""
    if backend not in ("pil", "cv2"):
        raise ValueError(f"unsupported image backend {backend!r}, "
                         "expected 'pil' or 'cv2'")
    global _image_backend
    _image_backend = backend


def get_image_backend() -> str:
    """reference: paddle.vision.get_image_backend."""
    return _image_backend


def image_load(path: str, backend=None):
    """reference: paddle.vision.image_load — load an image file with the
    configured backend (PIL here; cv2 is not in this environment)."""
    backend = backend or _image_backend
    if backend == "cv2":
        try:
            import cv2
            return cv2.imread(path)
        except ImportError:
            raise ImportError("cv2 backend requested but OpenCV is not "
                              "installed; use the 'pil' backend") from None
    from PIL import Image
    return Image.open(path)
