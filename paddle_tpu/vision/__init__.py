"""paddle_tpu.vision (reference parity: python/paddle/vision/)."""

from . import datasets, models, transforms
