"""ctypes bindings for the native runtime library (native/ptnative.cc).

Builds the shared library on first use with g++ (pybind11 is not in this
image; the C ABI + ctypes replaces the reference's pybind layer for these
components). All entry points degrade gracefully to Python fallbacks when
the toolchain is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_REPO_ROOT, "native", "ptnative.cc")
_SRC_PS = os.path.join(_REPO_ROOT, "native", "pt_ps.cc")
_LIB_PATH = os.path.join(_REPO_ROOT, "native", "libptnative.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _build() -> Optional[str]:
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
           _SRC, _SRC_PS, "-o", _LIB_PATH, "-lpthread", "-lrt"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return _LIB_PATH
    except (subprocess.CalledProcessError, FileNotFoundError,
            subprocess.TimeoutExpired):
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        path = _LIB_PATH
        stale = not os.path.exists(path) or any(
            os.path.exists(s) and os.path.getmtime(s) > os.path.getmtime(path)
            for s in (_SRC, _SRC_PS))
        if stale:
            path = _build()
        if path is None or not os.path.exists(path):
            _build_failed = True
            return None
        lib = ctypes.CDLL(path)
        lib.ptq_create.restype = ctypes.c_void_p
        lib.ptq_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                   ctypes.c_uint64]
        lib.ptq_open.restype = ctypes.c_void_p
        lib.ptq_open.argtypes = [ctypes.c_char_p]
        lib.ptq_push.restype = ctypes.c_int
        lib.ptq_push.argtypes = [ctypes.c_void_p,
                                 ctypes.POINTER(ctypes.c_uint8),
                                 ctypes.c_uint64]
        lib.ptq_pop.restype = ctypes.c_int64
        lib.ptq_pop.argtypes = [ctypes.c_void_p,
                                ctypes.POINTER(ctypes.c_uint8),
                                ctypes.c_uint64]
        lib.ptq_size.restype = ctypes.c_int
        lib.ptq_size.argtypes = [ctypes.c_void_p]
        lib.ptq_close.argtypes = [ctypes.c_void_p]
        lib.ptq_destroy.argtypes = [ctypes.c_void_p]
        lib.pt_crc32c.restype = ctypes.c_uint32
        lib.pt_crc32c.argtypes = [ctypes.POINTER(ctypes.c_uint8),
                                  ctypes.c_uint64, ctypes.c_uint32]
        lib.pt_u8_to_f32_norm.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_int64, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float)]
        lib.pt_aes128_ctr.restype = ctypes.c_int
        lib.pt_aes128_ctr.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint64]
        # --- parameter-server transport (native/pt_ps.cc) ---
        fp = ctypes.POINTER(ctypes.c_float)
        kp = ctypes.POINTER(ctypes.c_int64)
        lib.pt_ps_server_create.restype = ctypes.c_void_p
        lib.pt_ps_server_add_dense.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float]
        lib.pt_ps_server_add_sparse.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_float, ctypes.c_float, ctypes.c_uint64]
        lib.pt_ps_server_start.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                           ctypes.c_int]
        lib.pt_ps_server_start.restype = ctypes.c_int
        lib.pt_ps_server_port.argtypes = [ctypes.c_void_p]
        lib.pt_ps_server_port.restype = ctypes.c_int
        lib.pt_ps_server_stop.argtypes = [ctypes.c_void_p]
        lib.pt_ps_server_destroy.argtypes = [ctypes.c_void_p]
        lib.pt_ps_server_dense_read.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, fp, ctypes.c_uint64]
        lib.pt_ps_server_dense_read.restype = ctypes.c_int
        lib.pt_ps_server_sparse_size.argtypes = [ctypes.c_void_p,
                                                 ctypes.c_char_p]
        lib.pt_ps_server_sparse_size.restype = ctypes.c_int64
        lib.pt_ps_connect.restype = ctypes.c_void_p
        lib.pt_ps_connect.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.pt_ps_disconnect.argtypes = [ctypes.c_void_p]
        lib.pt_ps_pull_dense.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         fp, ctypes.c_uint64]
        lib.pt_ps_pull_dense.restype = ctypes.c_int
        lib.pt_ps_push_dense.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         fp, ctypes.c_uint64, ctypes.c_int]
        lib.pt_ps_push_dense.restype = ctypes.c_int
        lib.pt_ps_pull_sparse.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          kp, ctypes.c_uint64, fp,
                                          ctypes.c_int]
        lib.pt_ps_pull_sparse.restype = ctypes.c_int
        lib.pt_ps_push_sparse.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          kp, ctypes.c_uint64, fp,
                                          ctypes.c_int, ctypes.c_int]
        lib.pt_ps_push_sparse.restype = ctypes.c_int
        lib.pt_ps_table_dim.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.pt_ps_table_dim.restype = ctypes.c_int64
        lib.pt_ps_sparse_size.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.pt_ps_sparse_size.restype = ctypes.c_int64
        lib.pt_ps_barrier.argtypes = [ctypes.c_void_p]
        lib.pt_ps_barrier.restype = ctypes.c_int
        lib.pt_ps_stop_server.argtypes = [ctypes.c_void_p]
        lib.pt_ps_stop_server.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


_CAPI_SRC = os.path.join(_REPO_ROOT, "native", "pt_capi.cc")
_CAPI_LIB = os.path.join(_REPO_ROOT, "native", "libpt_infer.so")


_capi_lock = threading.Lock()


def _capi_loadable() -> bool:
    try:
        ctypes.CDLL(_CAPI_LIB)
        return True
    except OSError:
        return False


def build_capi() -> Optional[str]:
    """Build the C inference API (native/pt_capi.cc -> libpt_infer.so),
    the capi_exp-equivalent deployment library. Returns the .so path or
    None if the toolchain is unavailable."""
    import sysconfig
    with _capi_lock:
        fresh = (os.path.exists(_CAPI_LIB) and os.path.exists(_CAPI_SRC)
                 and os.path.getmtime(_CAPI_SRC) <=
                 os.path.getmtime(_CAPI_LIB))
        # a stale-or-foreign cached lib (e.g. linked against another
        # libpython) must be rebuilt, not returned
        if fresh and _capi_loadable():
            return _CAPI_LIB
        inc = sysconfig.get_paths()["include"]
        libdir = sysconfig.get_config_var("LIBDIR")
        pyver = f"python{sysconfig.get_config_var('py_version_short')}"
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _CAPI_SRC,
               f"-I{inc}", f"-L{libdir}", f"-l{pyver}",
               f"-Wl,-rpath,{libdir}", "-o", _CAPI_LIB]
        try:
            subprocess.run(cmd, check=True, capture_output=True,
                           timeout=180)
            return _CAPI_LIB if _capi_loadable() else None
        except (subprocess.CalledProcessError, FileNotFoundError,
                subprocess.TimeoutExpired):
            return None


class ShmQueue:
    """Shared-memory ring buffer for raw byte payloads (multiprocess
    DataLoader transport)."""

    def __init__(self, name: str, slot_size: int = 1 << 22,
                 n_slots: int = 8, create: bool = True):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("ptnative library unavailable")
        self._lib = lib
        self.name = name
        if create:
            self._h = lib.ptq_create(name.encode(), slot_size, n_slots)
        else:
            self._h = lib.ptq_open(name.encode())
        if not self._h:
            raise RuntimeError(f"failed to init ShmQueue {name!r}")
        self.slot_size = slot_size
        self._owner = create

    def push(self, payload: bytes) -> None:
        arr = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
        rc = self._lib.ptq_push(self._h, arr, len(payload))
        if rc == -1:
            raise RuntimeError("queue closed")
        if rc == -2:
            raise ValueError(f"payload {len(payload)} exceeds slot size")

    def push_array(self, arr: np.ndarray) -> None:
        self.push(arr.tobytes())

    def pop(self, cap: Optional[int] = None) -> Optional[bytes]:
        cap = cap or self.slot_size
        buf = (ctypes.c_uint8 * cap)()
        n = self._lib.ptq_pop(self._h, buf, cap)
        if n == -1:
            return None  # closed + drained
        if n == -2:
            raise ValueError("pop buffer too small")
        return bytes(bytearray(buf[:n]))

    def qsize(self) -> int:
        return self._lib.ptq_size(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.ptq_close(self._h)

    def destroy(self) -> None:
        if self._h:
            self._lib.ptq_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.destroy()
        except Exception:
            pass


_CRC32C_TABLE = None


def _crc32c_py(data: bytes, seed: int) -> int:
    # Same Castagnoli polynomial as pt_crc32c — checksums must be
    # machine-portable (they're embedded in encrypted artifacts)
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ (0x82F63B78 if c & 1 else 0)
            table.append(c)
        _CRC32C_TABLE = table
    c = seed ^ 0xFFFFFFFF
    for b in data:
        c = _CRC32C_TABLE[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def crc32c(data: bytes, seed: int = 0) -> int:
    lib = get_lib()
    if lib is None:
        return _crc32c_py(data, seed)
    arr = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
    return int(lib.pt_crc32c(arr, len(data), seed))


def u8_to_f32_norm(img: np.ndarray, mean, std) -> np.ndarray:
    """CHW uint8 image -> normalized float32 (native fused loop)."""
    lib = get_lib()
    img = np.ascontiguousarray(img, dtype=np.uint8)
    c = img.shape[0]
    hw = int(np.prod(img.shape[1:]))
    mean = np.asarray(mean, np.float32).ravel()
    std = np.asarray(std, np.float32).ravel()
    if mean.size == 1:
        mean = np.repeat(mean, c)
    if std.size == 1:
        std = np.repeat(std, c)
    if lib is None:
        return ((img.astype(np.float32) / 255.0 -
                 mean.reshape(-1, *([1] * (img.ndim - 1)))) /
                std.reshape(-1, *([1] * (img.ndim - 1))))
    out = np.empty(img.shape, np.float32)
    lib.pt_u8_to_f32_norm(
        img.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        c, hw, mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    return out
