"""paddle_tpu.profiler — profiling facade.

Reference parity: python/paddle/utils/profiler.py + fluid/profiler.py
context managers over the C++ event collector (platform/profiler.h). Host
events come from core.profiler; device traces delegate to jax.profiler
(XLA/TPU trace -> TensorBoard / Perfetto).
"""

import contextlib

import jax

from .core.profiler import (RecordEvent, disable_profiler, enable_profiler,
                            export_chrome_trace, profiler_guard,
                            profiler_events, reset_profiler)


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=None,
             trace_dir=None):
    """reference: fluid.profiler.profiler context manager."""
    with profiler_guard(trace_dir=trace_dir):
        yield
    if profile_path:
        export_chrome_trace(profile_path)


def start_profiler(state="All", trace_dir=None):
    enable_profiler()
    if trace_dir:
        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key=None, profile_path=None, trace_dir=False):
    if trace_dir:
        jax.profiler.stop_trace()
    disable_profiler()
    if profile_path:
        export_chrome_trace(profile_path)


def summary(top_k=20):
    """Aggregate host events by name: count/total/mean microseconds."""
    events = profiler_events()
    agg = {}
    for e in events:
        dur = e.end_us - e.start_us
        cnt, tot = agg.get(e.name, (0, 0.0))
        agg[e.name] = (cnt + 1, tot + dur)
    rows = sorted(((name, c, t, t / c) for name, (c, t) in agg.items()),
                  key=lambda r: -r[2])[:top_k]
    lines = [f"{'Event':<40}{'Calls':>8}{'Total(us)':>14}{'Avg(us)':>12}"]
    for name, c, t, avg in rows:
        lines.append(f"{name:<40}{c:>8}{t:>14.1f}{avg:>12.1f}")
    out = "\n".join(lines)
    print(out)
    return rows
