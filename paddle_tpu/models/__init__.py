"""Flagship model families (GPT/ERNIE-class LLMs, BERT)."""

from .gpt import (GPTAttention, GPTBlock, GPTConfig, GPTForCausalLM, GPTMLP,
                  GPTModel, PagedKVCache, StaticKVCache, ernie_10b,
                  gpt_125m, gpt_1p3b, gpt_350m, gpt_tiny,
                  paged_cache_create, paged_kv_append)
