"""Flagship model families (GPT/ERNIE-class LLMs, BERT)."""

from .gpt import (GPTAttention, GPTBlock, GPTConfig, GPTForCausalLM, GPTMLP,
                  GPTModel, ernie_10b, gpt_125m, gpt_1p3b, gpt_tiny)
