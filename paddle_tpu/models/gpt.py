"""GPT/ERNIE-class decoder-only transformer — the flagship model family.

Reference parity: the fleet-era GPT implementations the reference's hybrid
parallelism was built to train (Megatron-style TP layers
distributed/fleet/meta_parallel/parallel_layers/mp_layers.py + PP segments
pp_layers.py + sharding). Architecture choices follow the GPT-3/ERNIE 3.0
configs in BASELINE.md.

TPU-first: bf16 compute with fp32 layernorm/softmax, attention through
scaled_dot_product_attention (Pallas flash kernel on TPU), uniform blocks
so pipeline stages stack into a scanned [n_layer, ...] pytree, and every
parameter annotated with its hybrid-mesh PartitionSpec (dp×mp×pp×sp).
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import math
import threading
from typing import Any, NamedTuple, Optional

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import dispatch
from ..nn import functional as NF
from ..nn.common import Dropout, Embedding, Linear
from ..nn.container import LayerList
from ..nn.initializer import Normal
from ..nn.layer import Layer
from ..nn.norm import LayerNorm
from ..tensor import Tensor
from ..distributed.mp_layers import (ColumnParallelLinear,
                                     ParallelCrossEntropy,
                                     RowParallelLinear,
                                     VocabParallelEmbedding, _constrain)

F = dispatch.wrapped_ops


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    ffn_hidden_mult: int = 4
    dropout: float = 0.1
    attn_dropout: float = 0.1
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-5
    tie_word_embeddings: bool = True
    use_flash_attention: bool = True
    # None | "ring" | "ulysses" | "zigzag" (balanced causal ring: the
    # model permutes the sequence into the zigzag layout once at the
    # embedding boundary and back after the final norm)
    seq_parallel_mode: Optional[str] = None
    dtype: str = "float32"
    # MoE (beyond-reference): every `moe_every`-th block uses an
    # expert-parallel MoE FFN when moe_experts > 0
    moe_experts: int = 0
    moe_every: int = 2
    moe_top_k: int = 2
    # Chunked LM loss: compute logits+CE over sequence chunks of this many
    # positions under jax.checkpoint, so the [B, S, vocab] logits tensor
    # never materializes (peak activation drops from S*V to chunk*V per
    # example). 0 = off. Memory-saving analog of the reference's fused
    # c_softmax_with_cross_entropy (which also avoids a separate softmax
    # tensor); here it additionally avoids the full logits.
    loss_chunk_size: int = 0
    # Rematerialize each transformer block in backward (jax.checkpoint):
    # O(L) -> O(1) per-layer activation memory at ~33% extra FLOPs.
    # Single-chip analog of the reference's RecomputeOptimizer
    # (python/paddle/fluid/optimizer.py:5288). MoE blocks are NOT
    # rematerialized (their aux-loss side channel cannot escape
    # jax.checkpoint), so with moe_experts>0 only the dense blocks
    # drop out of the activation footprint.
    remat: bool = False
    # With remat on, rematerialize only blocks where
    # layer_idx % remat_every == 0: trades activation memory back for
    # fewer recomputed FLOPs when HBM has headroom (selective
    # checkpointing; remat_every=1 = every block).
    remat_every: int = 1
    # Selective remat: SAVE each attention mix's output so backward
    # recompute skips the flash forward — the block's dominant
    # recompute cost at long S — for only [B, S, H] of residual memory
    # per layer. Process-global (sets core.offload's remat saved names
    # at model build, consulted by the jax.checkpoint policy). DENSE
    # flash path only: ring/ulysses/zigzag sequence parallelism wraps
    # its hops in its own custom_vjp, and jax.checkpoint's
    # named-residual policy cannot see inside a custom_vjp — measured
    # bit-identical compiled memory on the S=32k zigzag scale proof
    # (SCALE_PROOF_LONGCTX.json variant_remat_save_attention).
    remat_save_attention: bool = False

    def __post_init__(self):
        if self.remat and self.remat_every < 1:
            raise ValueError(
                "remat_every must be >= 1 (1 = remat every block); to "
                "disable rematerialization set remat=False")

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads


# staged baseline configs (BASELINE.md: GPT-3 1.3B, ERNIE-3.0 10B)
def gpt_tiny(**kw):
    return GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                     num_heads=4, max_seq_len=128, dropout=0.0,
                     attn_dropout=0.0, **kw)


def gpt_125m(**kw):
    return GPTConfig(hidden_size=768, num_layers=12, num_heads=12, **kw)


def gpt_350m(**kw):
    """GPT-3 350M (BASELINE.md family): the largest decode config whose
    weight-only-int8 generate program compiles under the dev tunnel's
    remote-compile transport limit (the 1.3B int8 compile reproducibly
    kills it — BENCH_STAGED.json r5 int8_weight_only); bench_all's int8
    decode falls back here when 1.3B fails even on the chunked path."""
    return GPTConfig(hidden_size=1024, num_layers=24, num_heads=16,
                     max_seq_len=2048, **kw)


def gpt_1p3b(**kw):
    return GPTConfig(hidden_size=2048, num_layers=24, num_heads=16,
                     max_seq_len=2048, **kw)


def ernie_10b(**kw):
    return GPTConfig(hidden_size=4096, num_layers=48, num_heads=64,
                     max_seq_len=4096, **kw)


# -- fused decode hot path (r13) --------------------------------------------
#
# Trace-time switch, the same pattern as ops/pallas/paged_attention.py
# `head_sharding`: while active, the paged decode/verify paths fold
# their epilogues into fused ops — `paged_attention_fused` (attention +
# out-projection, one launch) inside GPTAttention, and callers sample
# through nn/decode.py `fused_sample_token` over `decode_hidden` so the
# [B, vocab] logits never materialize. THREAD-LOCAL because jit traces
# run on the calling thread and a fused serving engine may trace
# concurrently with an unfused one (two server threads). The switch
# changes the op composition, never the math: greedy outputs stay
# bit-identical to the unfused trace (pinned in
# tests/test_fused_decode.py).

_FUSED_DECODE = threading.local()


@contextlib.contextmanager
def fused_decode(enable: bool = True):
    """Route paged decode/verify traces through the fused kernels for
    the duration (wrap the jit-traced call, not the runtime one)."""
    prev = getattr(_FUSED_DECODE, "value", False)
    _FUSED_DECODE.value = bool(enable)
    try:
        yield
    finally:
        _FUSED_DECODE.value = prev


def fused_decode_active() -> bool:
    return bool(getattr(_FUSED_DECODE, "value", False))


class StaticKVCache(NamedTuple):
    """Preallocated per-layer KV buffer for fixed-shape decode.

    ``k``/``v``: [B, max_len, H, D] buffers; ``pos``: number of valid
    positions already written. Shapes never change across decode steps,
    so the whole generate loop compiles into one lax.scan (the serving
    analog of the reference inference engine's fused decoder kernels,
    e.g. operators/fused/multihead_matmul_op.cu's cache path)."""

    k: Any
    v: Any
    pos: Any


class PagedKVCache(NamedTuple):
    """Block-paged per-layer KV cache for ragged fixed-shape decode.

    KV lives in a pool of fixed-size pages (``k_pages``/``v_pages``:
    [num_pages + 1, page_size, H, D]; the LAST page is a reserved
    scratch page that masked/inactive writes land on, so recycled pages
    are never touched by slots that don't own them). ``page_table``
    ([B, max_pages] int32) maps each sequence's logical page index to a
    pool page; ``seq_lens`` ([B] int32) is each sequence's valid
    length. All shapes are static, so prefill + decode compile into one
    scanned program exactly like StaticKVCache — but attention walks
    only ceil(len/page) pages per sequence (ops/pallas/
    paged_attention.py), and a host-side allocator can hand pages from
    completed sequences to newly admitted ones mid-flight
    (inference/continuous_batching.py). int8 mode stores pages as int8
    with per-(position, head) abs-max scales (``k_scale``/``v_scale``:
    [num_pages + 1, page_size, H]; quantization/quant.py quantize_kv),
    halving the dominant decode HBM category."""

    k_pages: Any
    v_pages: Any
    k_scale: Any  # None when pages are float
    v_scale: Any
    page_table: Any
    seq_lens: Any

    @property
    def page_size(self) -> int:
        return self.k_pages.shape[1]


@functools.lru_cache(maxsize=None)
def _sharded_zeros_fn(sharding):
    """One jitted zeros-under-out_shardings program per output sharding
    (shape/dtype static, so jax's jit cache dedups repeated layers): a
    mesh engine creates 2*num_layers identically-shaped pools per
    build/resurrection, which must not each pay their own trace. Every
    EXECUTION still returns a fresh buffer — callers donate the pools,
    so the executable is shared, never the arrays."""
    import jax
    return jax.jit(jnp.zeros, static_argnums=(0, 1),
                   out_shardings=sharding)


def paged_cache_create(batch: int, num_pages: int, page_size: int,
                       num_heads: int, head_dim: int, dtype,
                       max_pages_per_seq: int, quantized: bool = False,
                       page_table=None, seq_lens=None,
                       kv_sharding=None) -> PagedKVCache:
    """Zero-filled pool (+1 reserved scratch page) with an optional
    pre-assigned page table; the default table hands sequence ``i``
    pages ``[i*mp, (i+1)*mp)`` contiguously (the single-request
    generate() layout — the continuous-batching engine supplies its
    allocator-managed table instead).

    ``kv_sharding``: an optional NamedSharding for the KV pools (the
    mesh-sharded engine passes heads-over-``mp``). The pools are
    created DIRECTLY under it via jit out_shardings — a serving-scale
    pool is sized for the whole mesh's HBM, so materializing it
    replicated first and resharding after would OOM the very
    deployments the mesh exists for. Scale pools (one rank lower)
    derive their sharding by dropping the trailing head-dim axis."""
    kv_dtype = jnp.int8 if quantized else dtype
    shape = (num_pages + 1, page_size, num_heads, head_dim)
    if kv_sharding is None:
        zeros = jnp.zeros
        scale_zeros = jnp.zeros
    else:
        from jax.sharding import NamedSharding, PartitionSpec
        spec3 = PartitionSpec(*tuple(kv_sharding.spec)[:3])
        scale_sharding = NamedSharding(kv_sharding.mesh, spec3)
        zeros = _sharded_zeros_fn(kv_sharding)
        scale_zeros = _sharded_zeros_fn(scale_sharding)

    k_pages = zeros(shape, kv_dtype)
    v_pages = zeros(shape, kv_dtype)
    if quantized:
        k_scale = scale_zeros(shape[:3], jnp.float32)
        v_scale = scale_zeros(shape[:3], jnp.float32)
    else:
        k_scale = v_scale = None
    if page_table is None:
        page_table = jnp.arange(
            batch * max_pages_per_seq,
            dtype=jnp.int32).reshape(batch, max_pages_per_seq)
    if seq_lens is None:
        seq_lens = jnp.zeros((batch,), jnp.int32)
    return PagedKVCache(k_pages, v_pages, k_scale, v_scale,
                        page_table, seq_lens)


def paged_kv_append(cache: PagedKVCache, k, v, valid_len=None):
    """Write ``s`` new tokens per sequence at positions seq_lens ..
    seq_lens+s-1 through the page table (one scatter per pool — fixed
    shapes, jit/scan-safe) and advance the lengths.

    ``valid_len`` ([B] int32, optional): ragged prefill — only the
    first valid_len[i] of the s tokens are real; the rest (right
    padding) are redirected to the reserved scratch page and the
    length advances by valid_len, so padded prompts never pollute a
    sequence's pages."""
    b, s = k.shape[:2]
    page = cache.page_size
    mp = cache.page_table.shape[1]
    scratch = cache.k_pages.shape[0] - 1
    pos = cache.seq_lens[:, None] + jnp.arange(s, dtype=jnp.int32)[None]
    if valid_len is None:
        valid = None
        new_lens = cache.seq_lens + s
    else:
        valid = jnp.arange(s, dtype=jnp.int32)[None] < valid_len[:, None]
        new_lens = cache.seq_lens + valid_len.astype(jnp.int32)
    pidx = jnp.clip(pos // page, 0, mp - 1)
    off = pos % page
    pages = jnp.take_along_axis(cache.page_table, pidx, axis=1)
    # over-capacity positions (pos beyond the table's mp*page) go to
    # the scratch page instead of silently overwriting the last real
    # page; lengths clamp below so attention never reads past what was
    # actually stored. In-tree callers size pools so this never fires
    # (generate: total = prompt + max_new; engine: admission checks
    # capacity) — this bounds the public-API failure mode.
    overflow = pos >= mp * page
    pages = jnp.where(overflow, scratch, pages)
    off = jnp.where(overflow, 0, off)
    if valid is not None:
        pages = jnp.where(valid, pages, scratch)
        off = jnp.where(valid, off, 0)
    new_lens = jnp.minimum(new_lens, mp * page)

    def put(pool, scales, val):
        if scales is None:
            return pool.at[pages, off].set(val.astype(pool.dtype)), None
        from ..quantization.quant import quantize_kv
        qv, sc = quantize_kv(val)
        return (pool.at[pages, off].set(qv),
                scales.at[pages, off].set(sc))

    k_pages, k_scale = put(cache.k_pages, cache.k_scale, k)
    v_pages, v_scale = put(cache.v_pages, cache.v_scale, v)
    return PagedKVCache(k_pages, v_pages, k_scale, v_scale,
                        cache.page_table, new_lens)


def paged_page_splice(pools, page, k_blocks, v_blocks,
                      ks_blocks=None, vs_blocks=None):
    """Restore spilled prefix pages into the engine's per-layer pools
    (r15 hierarchical prefix cache): write layer i's KV blocks
    (``k_blocks``/``v_blocks`` [nl, n, page, H, D], plus
    [nl, n, page, H] scales for int8 pools) into every pool at the n
    page indices ``page`` ([n] int32 — or a scalar with unbatched
    [nl, page, ...] blocks). ``pools`` is the engine's ``{"k": [...],
    "v": [...], "ks": [...], "vs": [...]}`` per-layer dict; returns
    the same structure. jit-friendly with ``page`` traced — one
    compile per batch bucket serves every restore — and pure, so the
    engine donates the pools for an in-place scatter exactly like the
    decode step's appends (inference/continuous_batching.py
    ``_splice_page``).

    Blocks always arrive in the POOL's layout: the r23 blob codecs
    (serving/prefix_cache.py ``pack_page_blob``/``unpack_page_blob``)
    decode wire formats (raw/int8/int4, quantization/quant.py
    ``KV_QMAX_*`` scale math) back to pool dtype on the host before
    this splice runs, so spill format never leaks into the jitted
    program — one compile serves every blob format."""
    from ..ops.nn_functional import paged_page_splice as _splice_one

    def put(pool_list, blocks):
        return [_splice_one(pool, blocks[i], page)
                for i, pool in enumerate(pool_list)]

    return {
        "k": put(pools["k"], k_blocks),
        "v": put(pools["v"], v_blocks),
        "ks": (list(pools["ks"]) if ks_blocks is None
               else put(pools["ks"], ks_blocks)),
        "vs": (list(pools["vs"]) if vs_blocks is None
               else put(pools["vs"], vs_blocks)),
    }


def multi_step_decode(step_fn, pools, table, lens, tokens, active,
                      rem, eos, num_steps: int, scratch: int,
                      spec=None, chunk=None):
    """Device-resident multi-step decode (r19, ROADMAP item 2): run up
    to ``num_steps`` fused decode steps in ONE on-device
    ``lax.while_loop`` program, so the host pays one launch + one
    readback per N tokens instead of per token — the launch/sync
    boundary was the remaining overhead after PR 8 fused the step to
    ~one program (the Neptune / FusionStitching locality argument one
    level up).

    r22 (ROADMAP item 3a/3b) moves the remaining BOUNDARY work into
    the program too, both optional and Python-static so ``spec=None,
    chunk=None`` traces byte-for-byte the r19 program:

    - ``spec`` (in-program speculative verify): a dict with static
      ``k``/``vocab`` and three closures + carries — ``draft_fn(hist,
      hist_len, cur) -> [B, k]`` proposals (nn/decode.py
      ``ngram_draft_tokens`` or self-draft, both pure gathers),
      ``verify_fn(pools, table, lens, toks [B, k+1], valid [B]) ->
      (accept, resid, full, pools)`` (the engine's fused
      ``verify_step`` math), and ``hist``/``hist_len`` [B, H]/[B]
      history buffers the accepted runs append to. Each iteration
      drafts, verifies all k+1 positions in one ragged chained-prefill
      pass, folds the accepted run through nn/decode.py
      ``masked_run_advance`` (EOS/budget truncation as masked
      carries), and REWINDS ``seq_lens`` past rejections inside the
      program — a k-token accepted run costs zero extra launches. The
      token ring widens to ``[B, num_steps, k+1]`` (−1 beyond each
      iteration's emitted share). Greedy only: acceptance is
      exact-match against the target's own argmax, so emission is
      bit-identical to per-token decode regardless of draft quality.

    - ``chunk`` (in-program chunked prefill): a dict with
      ``prefill_fn(pools, trow, slens, plen, ids) -> (nxt, pools)``
      (the engine's chained-prefill body — the ``q_offsets`` ragged
      paged-attention path), per-iteration ``ids [num_steps, bucket]``
      / ``valid`` / ``start`` / ``final`` schedules, and traced
      ``count``/``slot`` scalars. Iteration ``j < count`` advances the
      one half-prefilled slot's next page-aligned chunk inside the
      same program (``lax.cond`` skips the work on decode-only
      iterations); the FINAL chunk samples the slot's first token,
      writes it into the ring, and activates the slot for the next
      iteration's decode — a long prompt streams in without ever
      stalling a launch.

    ``step_fn(pools, table, lens, cur) -> (nxt, new_pools,
    new_lens)`` is the engine's SINGLE-TOKEN decode body — exactly the
    trace a ``multi_step=1`` launch runs — so every in-program
    iteration is bit-identical to one host-driven step by
    construction. The loop only adds the host bookkeeping the engine
    used to do between launches, in carry form:

    - masking: iteration inputs are re-derived per step — an inactive
      slot (finished mid-launch, half-prefilled, or empty) sees the
      scratch-page table at length 0, exactly how ``_decode_step``
      masks non-decoding slots, so its KV writes land on scratch and
      its pages are never touched;
    - early exit: the while_loop stops as soon as EVERY slot has
      stopped (EOS or budget — nn/decode.py ``masked_carry_advance``,
      the carry-form twin of the host's ``_finish_due``), so a batch
      that finishes at iteration j pays j steps, not N;
    - the token ring: each iteration writes its sampled tokens into a
      ``[B, num_steps]`` ring (−1 for masked slots), read back ONCE
      per launch — the host drains it through on_token/tracing at the
      next boundary while the device runs the following launch.

    Page growth stays host-owned and PRE-BOUND: the engine converts
    each slot's admission reservation into physical pages covering
    ``lens + min(num_steps, rem)`` positions BEFORE the launch (the
    PR 4 reservation machinery guarantees this cannot fail), so the
    page table is a constant of the program and in-program appends
    are pure index writes through it.

    Returns ``(ring, steps_done, cur, lens, active, pools)`` — final
    carry values the host folds back into its slot state at drain.
    ``ring`` is ``[B, num_steps]`` int32 (``spec=None`` — one token
    per iteration) or ``[B, num_steps, k+1]`` (in-program speculative:
    one accepted RUN per iteration)."""
    import jax

    from ..nn.decode import masked_carry_advance

    if spec is None and chunk is None:
        # r19 path, byte-for-byte (the escape-hatch contract: a plain
        # multi_step engine's trace is unchanged by r22)
        b = tokens.shape[0]
        ring0 = jnp.full((b, num_steps), -1, jnp.int32)
        emitted0 = jnp.zeros((b,), jnp.int32)
        rem = rem.astype(jnp.int32)
        eos = eos.astype(jnp.int32)

        def cond(carry):
            j, _cur, _lens, act, _emitted, _ring, _pl = carry
            return jnp.logical_and(j < num_steps, jnp.any(act))

        def body(carry):
            j, cur, lens_c, act, emitted, ring, pl = carry
            # per-iteration masking (the _decode_step contract):
            # inactive slots ride the fixed-shape step parked on the
            # scratch page at length 0 — defined zeros out, writes
            # land on scratch
            table_eff = jnp.where(act[:, None], table,
                                  scratch).astype(jnp.int32)
            lens_eff = jnp.where(act, lens_c, 0).astype(jnp.int32)
            nxt, pl, _ = step_fn(pl, table_eff, lens_eff, cur)
            col = jnp.where(act, nxt, -1).astype(jnp.int32)
            ring = jax.lax.dynamic_update_slice(ring, col[:, None],
                                                (0, j))
            # this iteration appended cur's KV for every active slot —
            # advance their lengths with the PRE-update mask
            lens_c = jnp.where(act, lens_c + 1, lens_c)
            cur, act, emitted = masked_carry_advance(nxt, cur, act,
                                                     emitted, rem, eos)
            return (j + 1, cur, lens_c, act, emitted, ring, pl)

        j, cur, lens_c, act, _emitted, ring, pl = jax.lax.while_loop(
            cond, body,
            (jnp.asarray(0, jnp.int32), tokens.astype(jnp.int32),
             lens.astype(jnp.int32), active, emitted0, ring0, pools))
        return ring, j, cur, lens_c, act, pl

    # -- r22 extended path: in-program speculative verify and/or
    # in-program chunked prefill ------------------------------------
    from ..nn.decode import masked_run_advance

    b = tokens.shape[0]
    k = int(spec["k"]) if spec is not None else 0
    width = k + 1
    if spec is not None:
        ring0 = jnp.full((b, num_steps, width), -1, jnp.int32)
        hist0 = spec["hist"].astype(jnp.int32)
        hlen0 = spec["hist_len"].astype(jnp.int32)
        hcap = hist0.shape[1]
    else:
        ring0 = jnp.full((b, num_steps), -1, jnp.int32)
    emitted0 = jnp.zeros((b,), jnp.int32)
    rem = rem.astype(jnp.int32)
    eos = eos.astype(jnp.int32)
    if chunk is not None:
        chunk_count = chunk["count"].astype(jnp.int32)
        chunk_slot = chunk["slot"].astype(jnp.int32)

    def cond(carry):
        j, _cur, _lens, act = carry[0], carry[1], carry[2], carry[3]
        alive = jnp.any(act)
        if chunk is not None:
            # chunk-only launches are legal (nothing decoding yet):
            # the loop runs until every scheduled chunk has landed
            alive = jnp.logical_or(alive, j < chunk_count)
        return jnp.logical_and(j < num_steps, alive)

    def body(carry):
        if spec is not None:
            (j, cur, lens_c, act, emitted, ring, pl, hist,
             hist_len) = carry
        else:
            j, cur, lens_c, act, emitted, ring, pl = carry
            hist = hist_len = None
        table_eff = jnp.where(act[:, None], table,
                              scratch).astype(jnp.int32)
        lens_eff = jnp.where(act, lens_c, 0).astype(jnp.int32)
        if spec is None:
            nxt, pl, _ = step_fn(pl, table_eff, lens_eff, cur)
            col = jnp.where(act, nxt, -1).astype(jnp.int32)
            ring = jax.lax.dynamic_update_slice(ring, col[:, None],
                                                (0, j))
            lens_c = jnp.where(act, lens_c + 1, lens_c)
            cur, act, emitted = masked_carry_advance(nxt, cur, act,
                                                     emitted, rem, eos)
        else:
            # draft clip: emit at most the remaining budget, exactly
            # the host _spec_step's k_eff = min(k, rem - 1) rule with
            # rem counted from the in-carry emitted total
            k_eff = jnp.clip(rem - emitted - 1, 0, k)
            valid = jnp.where(act, 1 + k_eff, 0).astype(jnp.int32)
            drafts = spec["draft_fn"](hist, hist_len, cur)
            drafts = jnp.clip(drafts.astype(jnp.int32), 0,
                              spec["vocab"] - 1)
            toks = jnp.concatenate([cur[:, None], drafts], axis=1)
            accept, _resid, full, pl = spec["verify_fn"](
                pl, table_eff, lens_eff, toks, valid)
            acc = jnp.logical_and(
                accept, jnp.arange(k)[None, :] < k_eff[:, None])
            nacc = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1),
                           axis=1)
            # greedy verify: resid == full[:, :-1], so the
            # correction/bonus token is full[:, n] in both the
            # n < k_eff and n == k_eff cases — the target's own next
            # token given the accepted prefix
            nxt = jnp.take_along_axis(full.astype(jnp.int32),
                                      nacc[:, None], axis=1)[:, 0]
            run = jnp.where(jnp.arange(width)[None, :] < nacc[:, None],
                            jnp.pad(drafts, ((0, 0), (0, 1))),
                            nxt[:, None])
            act_pre = act
            run_masked, emit_len, cur, act, emitted = \
                masked_run_advance(run, nacc + 1, cur, act, emitted,
                                   rem, eos)
            ring = jax.lax.dynamic_update_slice(
                ring, run_masked[:, None, :], (0, j, 0))
            # the in-program rewind: seq_lens advance past cur + the
            # accepted drafts ONLY — rejected positions fall back off
            # the valid range and the next iteration's verify appends
            # straight over their stale KV
            lens_c = jnp.where(act_pre, lens_c + nacc + 1, lens_c)
            # append the emitted run to the draft history
            for r in range(width):
                idx = jnp.minimum(hist_len + r, hcap - 1)
                put = jnp.logical_and(act_pre, r < emit_len)
                old = jnp.take_along_axis(hist, idx[:, None],
                                          axis=1)[:, 0]
                hist = hist.at[jnp.arange(b), idx].set(
                    jnp.where(put, run[:, r], old))
            hist_len = jnp.where(
                act_pre, jnp.minimum(hist_len + emit_len, hcap),
                hist_len)
        if chunk is not None:
            def run_chunk(op):
                if spec is not None:
                    cur, lens_c, act, emitted, ring, pl, hist, \
                        hist_len = op
                else:
                    cur, lens_c, act, emitted, ring, pl = op
                    hist = hist_len = None
                ids_j = jax.lax.dynamic_slice_in_dim(chunk["ids"], j,
                                                     1, 0)
                valid_j = jax.lax.dynamic_index_in_dim(
                    chunk["valid"], j, 0, keepdims=False)
                start_j = jax.lax.dynamic_index_in_dim(
                    chunk["start"], j, 0, keepdims=False)
                final_j = jax.lax.dynamic_index_in_dim(
                    chunk["final"], j, 0, keepdims=False)
                trow = jnp.take(table, chunk_slot[None],
                                axis=0).astype(jnp.int32)
                nxt_c, pl = chunk["prefill_fn"](
                    pl, trow, start_j[None], valid_j[None], ids_j)
                nxt_c = nxt_c.astype(jnp.int32)
                plen = start_j + valid_j
                onehot = jnp.arange(b) == chunk_slot
                upd = jnp.logical_and(final_j, onehot)
                # first-token stop rule (the host's _maybe_finish
                # after a final chunk's emission)
                slot_rem = jnp.take(rem, chunk_slot)
                slot_eos = jnp.take(eos, chunk_slot)
                stop = jnp.logical_or(nxt_c == slot_eos,
                                      slot_rem <= 1)
                cur = jnp.where(upd, nxt_c, cur)
                lens_c = jnp.where(upd, plen, lens_c)
                emitted = jnp.where(upd, 1, emitted)
                # activation: the promoted slot joins the decode from
                # the NEXT iteration (this iteration's decode already
                # ran on the pre-chunk mask)
                act = jnp.where(upd, jnp.logical_not(stop), act)
                if spec is not None:
                    ring = ring.at[chunk_slot, j, 0].set(
                        jnp.where(final_j, nxt_c,
                                  ring[chunk_slot, j, 0]))
                    hidx = jnp.minimum(plen, hcap - 1)
                    hist = hist.at[chunk_slot, hidx].set(
                        jnp.where(final_j, nxt_c,
                                  hist[chunk_slot, hidx]))
                    hist_len = jnp.where(upd, plen + 1, hist_len)
                    return (cur, lens_c, act, emitted, ring, pl,
                            hist, hist_len)
                ring = ring.at[chunk_slot, j].set(
                    jnp.where(final_j, nxt_c, ring[chunk_slot, j]))
                return (cur, lens_c, act, emitted, ring, pl)

            if spec is not None:
                ops = (cur, lens_c, act, emitted, ring, pl, hist,
                       hist_len)
            else:
                ops = (cur, lens_c, act, emitted, ring, pl)
            ops = jax.lax.cond(j < chunk_count, run_chunk,
                               lambda op: op, ops)
            if spec is not None:
                (cur, lens_c, act, emitted, ring, pl, hist,
                 hist_len) = ops
            else:
                cur, lens_c, act, emitted, ring, pl = ops
        if spec is not None:
            return (j + 1, cur, lens_c, act, emitted, ring, pl, hist,
                    hist_len)
        return (j + 1, cur, lens_c, act, emitted, ring, pl)

    init = [jnp.asarray(0, jnp.int32), tokens.astype(jnp.int32),
            lens.astype(jnp.int32), active, emitted0, ring0, pools]
    if spec is not None:
        init += [hist0, hlen0]
    out = jax.lax.while_loop(cond, body, tuple(init))
    j, cur, lens_c, act, _emitted, ring, pl = out[:7]
    return ring, j, cur, lens_c, act, pl


def _remat_block(block, x):
    """Run ``block`` under jax.checkpoint as ONE taped op: the pure kernel
    takes (hidden, *param_values) so the eager tape differentiates through
    it (and recomputes block activations in backward instead of storing
    them), while under jit capture it reduces to a plain checkpointed call.
    Analog of the reference's RecomputeFunction PyLayer
    (distributed/fleet/utils/recompute.py:63)."""
    import jax

    from ..nn.layer import functional_call

    named = list(block.named_parameters())
    names = [n for n, _ in named]
    params = [p for _, p in named]

    def kernel(h, *pvals):
        from ..core.offload import name_block_input, remat_policy
        state = {"params": dict(zip(names, pvals)), "buffers": {}}
        return jax.checkpoint(
            lambda s, hh: functional_call(
                block, s, Tensor(name_block_input(hh))),
            policy=remat_policy())(state, h)

    return dispatch.call_fn(kernel, "remat_block", True, (x, *params), {})


class GPTAttention(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        self.num_heads = c.num_heads
        self.head_dim = c.head_dim
        self.seq_mode = c.seq_parallel_mode
        init = Normal(std=c.initializer_range)
        self.qkv_proj = ColumnParallelLinear(
            c.hidden_size, 3 * c.hidden_size, gather_output=False)
        self.out_proj = RowParallelLinear(
            c.hidden_size, c.hidden_size, input_is_parallel=True)
        self.attn_dropout_p = c.attn_dropout
        self.use_flash = c.use_flash_attention

    def forward(self, x, cache=None, use_cache=False, prefill_len=None,
                prefill_chained=False):
        b, s, h = x.shape
        qkv = self.qkv_proj(x)  # [b, s, 3h] sharded over mp on last dim
        qkv = F["reshape"](qkv, (b, s, 3, self.num_heads, self.head_dim))
        q = qkv[:, :, 0]
        k = qkv[:, :, 1]
        v = qkv[:, :, 2]
        new_cache = None
        if use_cache and isinstance(cache, PagedKVCache):
            # Ragged paged decode path: append through the page table,
            # attend over only the pages each sequence owns.
            return self._decode_paged(q, k, v, cache, b, s, prefill_len,
                                      prefill_chained)
        if use_cache and isinstance(cache, StaticKVCache):
            # Fixed-shape decode path (scan/jit-able): write the new k/v
            # at pos into the preallocated buffers and attend over the
            # whole buffer with a validity mask.
            return self._decode_static(q, k, v, cache, b, s)
        if use_cache:
            if cache is not None:
                k = F["concat"]([cache[0], k], axis=1)
                v = F["concat"]([cache[1], v], axis=1)
            new_cache = (k, v)
        if self.seq_mode in ("ring", "ulysses", "zigzag") and \
                not use_cache:
            from ..distributed.sp import sequence_parallel_attention
            out = dispatch.call_fn(
                lambda qq, kk, vv: sequence_parallel_attention(
                    qq, kk, vv, mode=self.seq_mode, causal=True),
                "seq_parallel_attention", True, (q, k, v), {})
        else:
            # explicit both ways (the flag was silently ignored before
            # r4 — every earlier benched config actually ran flash):
            # True forces the flash kernel, False forces XLA attention
            out = F["scaled_dot_product_attention"](
                q, k, v, is_causal=True, dropout_p=self.attn_dropout_p,
                training=self.training, use_flash=bool(self.use_flash))
        # selective remat (config.remat_save_attention) is tagged at
        # the flash kernel's vjp residuals (out AND lse — see
        # pallas/flash_attention._flash_lse_vjp_fwd), not here: saving
        # out alone would still recompute the flash forward for lse
        out = F["reshape"](out, (b, s, self.num_heads * self.head_dim))
        out = self.out_proj(out)
        if use_cache:
            return out, new_cache
        return out

    def _decode_static(self, q, k, v, cache, b, s):
        """Single/multi-token decode against a preallocated KV buffer:
        k/v written at cache.pos via dynamic_update_slice, attention over
        the full buffer masked to positions < pos + s. Fixed shapes
        throughout — the building block of the jitted generate loop."""
        import jax

        def upd(buf, val, p):
            return jax.lax.dynamic_update_slice(
                buf, val.astype(buf.dtype), (0, p, 0, 0))

        k_buf = dispatch.call_fn(upd, "kv_cache_update", True,
                                 (cache.k, k, cache.pos), {})
        v_buf = dispatch.call_fn(upd, "kv_cache_update", True,
                                 (cache.v, v, cache.pos), {})
        total = k_buf.shape[1]

        def attend(qq, kk, vv, p):
            # causal over absolute positions: query i sits at p + i;
            # shared sdpa does the fp32-softmax attention under the mask
            kpos = jnp.arange(total)[None, None, None, :]
            qpos = p + jnp.arange(qq.shape[1])[None, None, :, None]
            from .. import ops
            return ops.nn_functional.scaled_dot_product_attention(
                qq, kk, vv, attn_mask=kpos <= qpos, use_flash=False)

        out = dispatch.call_fn(attend, "kv_cache_attention", True,
                               (q, k_buf, v_buf, cache.pos), {})
        out = F["reshape"](out, (b, s, self.num_heads * self.head_dim))
        out = self.out_proj(out)
        return out, StaticKVCache(k_buf, v_buf, cache.pos + s)

    def _decode_paged(self, q, k, v, cache, b, s, prefill_len=None,
                      prefill_chained=False):
        """Paged decode/prefill: k/v append through the page table
        (ragged right-padding redirected to the scratch page), then

        - s == 1 (decode): the ragged paged-attention op — the Pallas
          page-walk kernel on TPU, its dense-gather reference on the
          CPU fast lane (ops/pallas/paged_attention.py);
        - s > 1 with ``prefill_len`` (scheduler/generate prefill, which
          guarantees a FRESH slot — seq_lens == 0 before the chunk):
          dense causal attention over THIS chunk's k/v only. Causal +
          right padding means valid tokens attend exactly their own
          prefix; padded tokens' outputs are discarded by the caller
          and their KV never reaches a real page.
        - s > 1 with ``prefill_len`` AND ``prefill_chained`` (the
          prefix-cache suffix prefill, serving/prefix_cache.py, AND
          every non-first chunk of the engine's chunked prefill,
          inference/continuous_batching.py ``prefill_chunk_tokens``):
          the slot STARTS at seq_lens > 0 — page-table entries below
          that length hold already-populated KV, whether shared prefix
          pages or this request's own prior chunks (the same "already
          stored" case) — so the ragged right-padded chunk is appended
          via valid_len and attends the stored prefix PLUS itself
          through the reference paged attention with q_offsets = old
          seq_lens. Right-padded query rows produce garbage that the
          caller discards; their KV lands on the scratch page, never
          on a shared page.
        - s > 1 without ``prefill_len`` (public forward() continuation
          against a possibly NON-empty cache): the reference paged
          attention with per-sequence q_offsets — it attends the full
          stored prefix plus the chunk, so multi-chunk appends are
          correct instead of silently chunk-local.

        Prefill attends the un-quantized k/v even in int8 mode (exact,
        and free — the dense path already has them in registers);
        decode reads back the quantized pages, which is the lossy step
        the int8 parity tests bound. The chained prefill reads the
        prefix back from pages, so in int8 mode its prefix keys are
        the quantized ones — the same values decode would have read."""
        old_lens = cache.seq_lens
        if prefill_len is None:
            new_cache = dispatch.call_fn(
                lambda c, kk, vv: tuple(paged_kv_append(c, kk, vv)),
                "paged_kv_append", True, (cache, k, v), {})
        else:
            new_cache = dispatch.call_fn(
                lambda c, kk, vv, pl_: tuple(paged_kv_append(
                    c, kk, vv, valid_len=pl_)),
                "paged_kv_append", True, (cache, k, v, prefill_len), {})
        new_cache = PagedKVCache(*new_cache)
        # fused epilogue (r13): under an active fused_decode() trace,
        # the paged-attention branches fold softmax-normalize +
        # head-concat + out-projection into ONE op and return the
        # attention block's output directly — same math, one launch
        # (the dense fresh-prefill branch keeps its exact pre-r13
        # program; it is not the decode hot path)
        fw = (self._fused_epilogue_params() if fused_decode_active()
              else None)
        if s == 1:
            if fw is not None:
                out = F["paged_attention_fused"](
                    q, new_cache.k_pages, new_cache.v_pages,
                    new_cache.page_table, new_cache.seq_lens,
                    fw[0], fw[1], k_scale=new_cache.k_scale,
                    v_scale=new_cache.v_scale)
                return out, new_cache
            out = F["paged_attention"](
                q, new_cache.k_pages, new_cache.v_pages,
                new_cache.page_table, new_cache.seq_lens,
                k_scale=new_cache.k_scale, v_scale=new_cache.v_scale)
        elif prefill_len is not None and not prefill_chained:
            out = F["scaled_dot_product_attention"](
                q, k, v, is_causal=True, dropout_p=0.0,
                training=False, use_flash=bool(self.use_flash))
        else:
            if fw is not None:
                out = F["paged_attention_fused"](
                    q, new_cache.k_pages, new_cache.v_pages,
                    new_cache.page_table, new_cache.seq_lens,
                    fw[0], fw[1], k_scale=new_cache.k_scale,
                    v_scale=new_cache.v_scale, q_offsets=old_lens)
                return out, new_cache
            out = F["paged_attention"](
                q, new_cache.k_pages, new_cache.v_pages,
                new_cache.page_table, new_cache.seq_lens,
                k_scale=new_cache.k_scale, v_scale=new_cache.v_scale,
                q_offsets=old_lens)
        out = F["reshape"](out, (b, s, self.num_heads * self.head_dim))
        out = self.out_proj(out)
        return out, new_cache

    def _fused_epilogue_params(self):
        """(weight, bias) of a FUSABLE out-projection, else None: the
        epilogue folds only a plain fp matmul head ([E, E] weight, the
        RowParallelLinear layout). A converted projection (e.g.
        quantization's WeightOnlyInt8Linear, whose weight lives in
        int8 buffers with an output-scale epilogue of its own) keeps
        the unfused composition — correctness over fusion."""
        import jax.numpy as _jnp
        w = getattr(self.out_proj, "weight", None)
        if w is None:
            return None
        wv = w.value if isinstance(w, Tensor) else w
        if wv is None or not _jnp.issubdtype(wv.dtype, _jnp.floating):
            return None
        if wv.shape[0] != self.num_heads * self.head_dim:
            return None
        return w, getattr(self.out_proj, "bias", None)


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        inner = c.ffn_hidden_mult * c.hidden_size
        self.fc_in = ColumnParallelLinear(c.hidden_size, inner,
                                          gather_output=False)
        self.fc_out = RowParallelLinear(inner, c.hidden_size,
                                        input_is_parallel=True)

    def forward(self, x):
        return self.fc_out(F["gelu"](self.fc_in(x), True))


class GPTBlock(Layer):
    """Pre-norm transformer block; uniform across the stack so pipeline
    stages can scan a stacked params pytree."""

    def __init__(self, config: GPTConfig, layer_idx: int = 0):
        super().__init__()
        self.ln_1 = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        if (config.moe_experts > 0 and
                layer_idx % config.moe_every == config.moe_every - 1):
            from ..distributed.moe import MoELayer
            self.mlp = MoELayer(
                config.hidden_size,
                config.ffn_hidden_mult * config.hidden_size,
                num_experts=config.moe_experts, top_k=config.moe_top_k)
        else:
            self.mlp = GPTMLP(config)
        self.dropout = Dropout(config.dropout)

    def forward(self, x, cache=None, use_cache=False, prefill_len=None,
                prefill_chained=False):
        if use_cache:
            a, new_cache = self.attn(self.ln_1(x), cache, use_cache=True,
                                     prefill_len=prefill_len,
                                     prefill_chained=prefill_chained)
            x = x + self.dropout(a)
            x = x + self.dropout(self.mlp(self.ln_2(x)))
            return x, new_cache
        x = x + self.dropout(self.attn(self.ln_1(x)))
        x = x + self.dropout(self.mlp(self.ln_2(x)))
        return x


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        c = config
        # Selective remat is scoped to THIS model's forward trace
        # (override_remat_saved_names around forward): a model that
        # never opted in neither clears nor inherits another model's
        # selection, and a direct set_remat_saved_names() call stays in
        # force for models built with remat_save_attention=False.
        from ..core.offload import ATTN_OUT_NAME
        self._remat_names = ((ATTN_OUT_NAME,) if c.remat_save_attention
                             else None)
        init = Normal(std=c.initializer_range)
        self.wte = VocabParallelEmbedding(c.vocab_size, c.hidden_size)
        self.wpe = Embedding(c.max_seq_len, c.hidden_size)
        self.wpe.weight.pspec = P()
        self.drop = Dropout(c.dropout)
        self.h = LayerList([GPTBlock(c, i)
                            for i in range(c.num_layers)])
        self.ln_f = LayerNorm(c.hidden_size, epsilon=c.layer_norm_epsilon)

    def forward(self, input_ids, position_ids=None, caches=None,
                use_cache=False, prefill_lens=None,
                prefill_chained=False):
        if self._remat_names is not None:
            from ..core.offload import override_remat_saved_names
            with override_remat_saved_names(self._remat_names):
                return self._forward(input_ids, position_ids, caches,
                                     use_cache, prefill_lens,
                                     prefill_chained)
        return self._forward(input_ids, position_ids, caches, use_cache,
                             prefill_lens, prefill_chained)

    def _forward(self, input_ids, position_ids=None, caches=None,
                 use_cache=False, prefill_lens=None,
                 prefill_chained=False):
        use_cache = use_cache or caches is not None
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = F["arange"](s, dtype="int32")
            offset = 0
            if caches is not None and caches[0] is not None:
                c0 = caches[0]
                if isinstance(c0, StaticKVCache):
                    offset = c0.pos
                elif isinstance(c0, PagedKVCache):
                    # ragged: each sequence continues from ITS length
                    lens = c0.seq_lens
                    offset = F["unsqueeze"](
                        lens if isinstance(lens, Tensor) else Tensor(lens),
                        1)
                else:
                    offset = c0[0].shape[1]
                position_ids = position_ids + offset
            if len(position_ids.shape) == 1:
                position_ids = F["expand"](
                    F["unsqueeze"](position_ids, 0), (b, s))
        x = self.wte(input_ids) + self.wpe(position_ids)
        # shard activations: batch over dp(+sharding), seq over sep
        x = _constrain(x, ("dp", "sharding"), "sep", None)
        x = self.drop(x)
        zig = (self.config.seq_parallel_mode == "zigzag" and
               not use_cache and self._sep_degree() > 1)
        if zig:
            x = self._zigzag(x, s)
        if caches is None and use_cache:
            caches = [None] * len(self.h)
        new_caches = [] if use_cache else None
        for i, block in enumerate(self.h):
            if use_cache:
                x, nc = block(x, caches[i], use_cache=True,
                              prefill_len=prefill_lens,
                              prefill_chained=prefill_chained)
                new_caches.append(nc)
            elif self.config.remat and not hasattr(block.mlp, "aux_loss") \
                    and i % self.config.remat_every == 0:
                x = _remat_block(block, x)
            else:
                x = block(x)
        x = self.ln_f(x)
        if zig:
            x = self._zigzag(x, s, inverse=True)
        if use_cache:
            return x, new_caches
        return x

    def _sep_degree(self) -> int:
        from ..distributed.topology import get_hybrid_communicate_group
        hcg = get_hybrid_communicate_group()
        return dict(hcg.mesh.shape).get("sep", 1) if hcg is not None else 1

    def _zigzag(self, x, s, inverse=False):
        """One boundary re-layout puts the WHOLE block stack in the
        zigzag sequence layout (every non-attention op is positionwise;
        attention runs the balanced zigzag ring); the inverse after the
        final norm restores the public order, so the LM loss shift is
        untouched. Chunk-level split+concat (not a gather — shard-
        aligned slices lower to collective-permutes under GSPMD; a
        sharded-S gather trips the TPU SPMD partitioner), two per step
        instead of per-layer re-layouts."""
        from ..distributed.sp import zigzag_reorder
        n = self._sep_degree()
        x = dispatch.call_fn(
            lambda h: zigzag_reorder(h, n, axis=1, inverse=inverse),
            "zigzag_permute", True, (x,), {})
        return _constrain(x, ("dp", "sharding"), "sep", None)


class GPTForCausalLM(Layer):
    """GPT with a (vocab-sharded) LM head + parallel CE loss."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=False)
        self.loss_fn = ParallelCrossEntropy()

    def logits(self, hidden):
        if self.lm_head is not None:
            return self.lm_head(hidden)
        return F["matmul"](hidden, self.gpt.wte.weight, transpose_y=True)

    def head_params(self):
        """``(weight, transpose_y, bias)`` of the lm_head for the fused
        streaming sampler (nn/decode.py ``fused_sample_token``), or
        None when the head is not a plain fp matmul (e.g. an
        int8-converted lm_head) — callers then fall back to
        :meth:`logits`. Tied embeddings expose the [V, D] wte weight
        with ``transpose_y=True``, exactly the :meth:`logits` math."""
        import jax.numpy as _jnp
        if self.lm_head is None:
            return self.gpt.wte.weight, True, None
        w = getattr(self.lm_head, "weight", None)
        if w is None:
            return None
        wv = w.value if isinstance(w, Tensor) else w
        if wv is None or not _jnp.issubdtype(wv.dtype, _jnp.floating):
            return None
        return w, False, getattr(self.lm_head, "bias", None)

    def decode_hidden(self, input_ids, caches, prefill_lens=None,
                      prefill_chained=False):
        """Cached forward returning FINAL HIDDEN STATES instead of
        logits — the fused decode hot path's model entry: callers
        sample straight from the hidden row via the streaming lm_head
        (``fused_sample_token``), so the [B, S, vocab] logits tensor
        never materializes. Returns ``(hidden [B, S, D],
        new_caches)``."""
        return self.gpt(input_ids, None, caches,
                        prefill_lens=prefill_lens,
                        prefill_chained=prefill_chained)

    def _chunked_lm_loss(self, hidden, labels, chunk):
        """Mean next-token CE without materializing full logits: scan over
        sequence chunks; each chunk's logits+CE run under jax.checkpoint,
        so backward recomputes the chunk logits instead of storing them.
        Dispatched as ONE taped op over (hidden, labels, head params) so
        eager backward differentiates through it."""
        import jax

        from ..autograd.engine import no_grad
        from ..nn.layer import bind_state

        head = self.lm_head
        if head is not None:
            hp = list(head.named_parameters())
            names = [n for n, _ in hp]
            params = [p for _, p in hp]
        else:
            names = None
            params = [self.gpt.wte.weight]

        def kernel(hid, lab, *pvals):
            lab = lab[:, 1:].astype(jnp.int32)
            hid = hid[:, :-1]
            b, s, d = hid.shape
            pad = (-s) % chunk
            if pad:
                hid = jnp.pad(hid, ((0, 0), (0, pad), (0, 0)))
                lab = jnp.pad(lab, ((0, 0), (0, pad)),
                              constant_values=-100)  # ignore_index
            nc = hid.shape[1] // chunk
            hid = hid.reshape(b, nc, chunk, d).swapaxes(0, 1)  # [nc,B,C,D]
            lab = lab.reshape(b, nc, chunk).swapaxes(0, 1)

            def apply_head(h):
                if head is None:
                    return h @ pvals[0].T
                with bind_state(head, {"params": dict(zip(names, pvals)),
                                       "buffers": {}}):
                    out = head(Tensor(h))
                return out.value if isinstance(out, Tensor) else out

            @jax.checkpoint
            def chunk_fn(h, l):  # noqa: E741
                per = self.loss_fn(Tensor(apply_head(h)), Tensor(l))
                per = per.value if isinstance(per, Tensor) else per
                # zero the scan-padding slots; user ignore_index positions
                # are already zeroed by the loss (and, like the full-logits
                # F["mean"] path, still count in the denominator)
                return jnp.where(l != -100, per, 0.0).sum()

            def body(tot, inp):
                return tot + chunk_fn(*inp), None

            with no_grad():
                tot, _ = jax.lax.scan(
                    body, jnp.asarray(0.0, jnp.float32), (hid, lab))
            return tot / (b * s)

        return dispatch.call_fn(kernel, "chunked_lm_loss", True,
                                (hidden, labels, *params), {})

    def forward(self, input_ids, labels=None, position_ids=None,
                caches=None, prefill_lens=None, prefill_chained=False):
        if caches is not None:
            hidden, new_caches = self.gpt(input_ids, position_ids, caches,
                                          prefill_lens=prefill_lens,
                                          prefill_chained=prefill_chained)
            return self.logits(hidden), new_caches
        hidden = self.gpt(input_ids, position_ids)
        if labels is None:
            return self.logits(hidden)
        # next-token LM loss
        if self.config.loss_chunk_size:
            loss = self._chunked_lm_loss(hidden, labels,
                                         self.config.loss_chunk_size)
        else:
            logits = self.logits(hidden)
            shift_logits = logits[:, :-1]
            shift_labels = labels[:, 1:]
            loss = F["mean"](self.loss_fn(shift_logits, shift_labels))
        # MoE load-balancing aux losses, if any blocks are MoE
        for block in self.gpt.h:
            aux = getattr(block.mlp, "aux_loss", None)
            if aux is not None:
                a = block.mlp.aux_loss()
                if a is not None:
                    loss = loss + a
        return loss

    def verify_step(self, input_ids, caches, valid_len):
        """Speculative-decoding verify forward over paged slots.

        ``input_ids``: [B, s] = ``[cur, d_0, .., d_{s-2}]`` per
        sequence — the pending token plus ``s-1`` draft tokens.
        ``caches``: per-layer PagedKVCache whose ``seq_lens`` are the
        PRE-verify lengths. ``valid_len``: [B] int32, how many of the
        ``s`` tokens are real for each sequence (ragged draft windows;
        0 parks an inactive slot — its writes land on the reserved
        scratch page).

        One forward scores ALL ``s`` positions: the chunk is appended
        through ``paged_kv_append`` (valid_len redirects the ragged
        tail to the scratch page, so rejected-draft KV never lands
        outside the sequence's own pages) and attends the stored
        prefix plus itself through the chained-prefill paged-attention
        path (``q_offsets`` = old seq_lens). Position ``j``'s logits
        are therefore exactly the vanilla decode logits after
        ``cur, d_0..d_{j-1}`` — the bit-identical greedy contract the
        speculative engine pins. Returns ``(logits [B, s, V],
        new_caches)``; the caller keeps host-side lengths and rolls
        back past the longest accepted prefix (rejected positions are
        simply never attended and are overwritten by the next
        append)."""
        return self.forward(input_ids, caches=caches,
                            prefill_lens=valid_len,
                            prefill_chained=True)

    def generate(self, input_ids, max_new_tokens: int = 20,
                 temperature: float = 1.0, top_k: Optional[int] = None,
                 key=None, use_jit: bool = False,
                 kv_cache: str = "static", page_size: int = 64,
                 compile_mode: str = "whole"):
        """Greedy/top-k sampling with kv cache. ``use_jit`` compiles the
        WHOLE generation (prefill + lax.scan decode over a StaticKVCache)
        into one device launch — the serving hot path; the eager loop
        stays as the debuggable reference.

        ``kv_cache``: "static" (dense preallocated buffers), "paged"
        (block-paged pool + page table — the ragged decode path,
        identical greedy tokens, pinned in tests/test_paged_attention),
        or "paged_int8" (int8 KV pages, half the streamed KV bytes).
        Paged modes require ``use_jit``. ``compile_mode``: "whole" (one
        program) or "chunked" — compile ONE per-block decode function
        (the uniform blocks share it) plus small embed/head programs,
        for models whose whole-generate compile exceeds the remote-
        compile transport (the 1.3B int8 failure in BENCH_STAGED.json);
        slower to launch, but every component program is ~num_layers x
        smaller."""
        import jax
        from ..core.rng import next_key
        from ..tensor import Tensor

        if kv_cache not in ("static", "paged", "paged_int8"):
            raise ValueError(f"unknown kv_cache mode {kv_cache!r}")
        if compile_mode not in ("whole", "chunked"):
            raise ValueError(f"unknown compile_mode {compile_mode!r}")
        if kv_cache != "static" and not use_jit:
            raise ValueError("paged kv_cache requires use_jit=True")
        if compile_mode == "chunked" and not use_jit:
            raise ValueError("compile_mode='chunked' requires "
                             "use_jit=True (it IS a compile strategy)")
        if kv_cache != "static" and compile_mode == "chunked":
            raise ValueError(
                "compile_mode='chunked' decodes over the dense "
                "StaticKVCache only (its per-block programs exist to "
                "shrink compiles, not to change the cache layout)")
        if use_jit and compile_mode == "chunked" and max_new_tokens > 0:
            return self._generate_chunked(input_ids, max_new_tokens,
                                          temperature, top_k, key)
        if use_jit and max_new_tokens > 0:
            return self._generate_jit(input_ids, max_new_tokens,
                                      temperature, top_k, key,
                                      kv_cache=kv_cache,
                                      page_size=page_size)
        if max_new_tokens <= 0:
            return input_ids
        self.eval()
        # the eager loop samples through the ONE shared sampler
        # (nn/decode.py sample_token — r13 consolidation: the same
        # call the jitted scan, the chunked generate and the serving
        # engine make; previously these four lines lived here inline
        # with their own key-split order)
        from ..nn.decode import sample_token
        caches = [None] * self.config.num_layers
        ids = input_ids
        logits, caches = self.forward(ids, caches=caches)
        out_ids = [ids]
        cur = logits[:, -1]
        key_raw = key.value if isinstance(key, Tensor) else key
        if temperature != 0.0 and key_raw is None:
            key_raw = next_key()
        for _ in range(max_new_tokens):
            tok, key_raw = sample_token(
                cur.value if isinstance(cur, Tensor) else cur,
                float(temperature), top_k, key_raw)
            nxt = Tensor(tok[:, None].astype(jnp.int32))
            out_ids.append(nxt)
            logits, caches = self.forward(nxt, caches=caches)
            cur = logits[:, -1]
        return F["concat"](out_ids, axis=1)

    def _generate_jit(self, input_ids, max_new_tokens, temperature, top_k,
                      key, kv_cache: str = "static", page_size: int = 64):
        """One-launch generation: prefill writes the prompt's KV into
        preallocated buffers (dense or block-paged), then lax.scan runs
        fixed-shape decode steps (TPU-native replacement for the
        reference inference engine's decoder loop — no Python between
        tokens)."""
        import jax

        from ..autograd.engine import no_grad
        from ..core.rng import next_key
        from ..nn.layer import bind_state, functional_state

        self.eval()
        ids_raw = input_ids.value if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        b, s = ids_raw.shape
        total = s + max_new_tokens
        cfg = self.config
        nh, hd, nl = cfg.num_heads, cfg.head_dim, cfg.num_layers
        state = functional_state(self)
        dt = state["params"]["gpt.wte.weight"].dtype
        key_raw = key.value if isinstance(key, Tensor) else key
        if key_raw is None:
            key_raw = next_key()
        temp, tk = float(temperature), top_k
        pages_per_seq = -(-total // page_size)

        def raw(t):
            return t.value if isinstance(t, Tensor) else t

        def raw_cache(c):
            if isinstance(c, StaticKVCache):
                return StaticKVCache(raw(c.k), raw(c.v), raw(c.pos))
            return PagedKVCache(*[None if f is None else raw(f)
                                  for f in c])

        def make_caches():
            if kv_cache == "static":
                return [StaticKVCache(jnp.zeros((b, total, nh, hd), dt),
                                      jnp.zeros((b, total, nh, hd), dt),
                                      jnp.asarray(0, jnp.int32))
                        for _ in range(nl)]
            return [paged_cache_create(
                b, b * pages_per_seq, page_size, nh, hd, dt,
                pages_per_seq, quantized=(kv_cache == "paged_int8"))
                for _ in range(nl)]

        # fused decode hot path (r13): when the lm_head is a plain fp
        # matmul, every step samples STRAIGHT from the final hidden row
        # through the streaming lm_head (nn/decode.py
        # fused_sample_token — greedy tokens bit-identical to
        # argmax(logits) by the first-index tie rule), and paged traces
        # additionally fold the attention epilogue (fused_decode()).
        # A non-fusable head (e.g. int8-converted lm_head) keeps the
        # exact pre-r13 logits path.
        use_fused = self.head_params() is not None

        def fwd_tok(params, ids, caches, k):
            # paged prefill chunks (s > 1) pass an explicit full-length
            # prefill_lens: generate() always starts from a FRESH pool,
            # so the chunk-local dense fast path applies (forward()
            # without it assumes a possibly non-empty cache and takes
            # the general full-prefix path)
            plens = None
            if kv_cache != "static" and ids.shape[1] > 1:
                plens = jnp.full((ids.shape[0],), ids.shape[1],
                                 jnp.int32)
            with bind_state(self, {"params": params, "buffers": {}}), \
                    no_grad():
                if use_fused:
                    from ..nn.decode import fused_sample_token
                    hidden, nc = self.decode_hidden(Tensor(ids), caches,
                                                    prefill_lens=plens)
                    w, ty, bias = self.head_params()
                    nxt, k = fused_sample_token(
                        raw(hidden)[:, -1], raw(w), temp, tk, k,
                        transpose_y=ty,
                        bias=None if bias is None else raw(bias))
                else:
                    from ..nn.decode import sample_token
                    logits, nc = self.forward(Tensor(ids), caches=caches,
                                              prefill_lens=plens)
                    nxt, k = sample_token(raw(logits)[:, -1], temp, tk, k)
            return nxt, [raw_cache(c) for c in nc], k

        def run(params, ids, k):
            # single-device program: hybrid-mesh activation constraints
            # must not leak into this trace. With a fleet group live in
            # the process they hand the GSPMD partitioner mp/dp
            # annotations with no in_shardings to anchor them, and it
            # has been observed to insert an all-reduce over mp on the
            # REPLICATED token output — emitted ids came back exactly
            # mp-times too large while the scan carry stayed correct.
            from ..distributed.mp_layers import no_sharding_constraints
            fuse_attn = (fused_decode() if use_fused and
                         kv_cache != "static"
                         else contextlib.nullcontext())
            with no_sharding_constraints(), fuse_attn:
                caches = make_caches()
                nxt, caches, k = fwd_tok(params, ids, caches, k)

                def body(carry, _):
                    cur, cs, kk = carry
                    nxt2, cs, kk = fwd_tok(params, cur[:, None], cs, kk)
                    return (nxt2, cs, kk), cur

                (last, _, _), toks = jax.lax.scan(
                    body, (nxt, caches, k), None,
                    length=max_new_tokens - 1)
                # toks: [N-1, B] tokens fed at each step; `last` is
                # token N
                all_new = jnp.concatenate(
                    [toks, last[None]], axis=0).swapaxes(0, 1)  # [B, N]
                return jnp.concatenate([ids, all_new], axis=1)

        sig = (b, s, max_new_tokens, temp, tk, kv_cache, page_size)
        cache = getattr(self, "_gen_jit_cache", None)
        if cache is None:
            cache = self._gen_jit_cache = {}
        if sig not in cache:
            cache[sig] = jax.jit(run)
        out = cache[sig](state["params"], ids_raw, key_raw)
        return Tensor(out)

    def _generate_chunked(self, input_ids, max_new_tokens, temperature,
                          top_k, key):
        """Chunked-compile generation: instead of one whole-program
        compile (prefill + scanned decode — the program whose int8
        1.3B variant reproducibly kills the dev tunnel's remote-compile
        transport, BENCH_STAGED.json r5), compile THREE small programs:
        embed, ONE per-block step (the uniform blocks share the
        compiled function — per-layer params are just different
        arguments), and the LM head. Each program is ~num_layers x
        smaller than the monolith; compiles are wrapped in a transient-
        error RetryPolicy (distributed/resilience.py). The price is a
        Python-level launch per layer per token — this path exists to
        GET a measured number past a compile-transport limit, not to
        win the latency race. Greedy/top-k token stream matches
        use_jit=True bit-for-bit at temperature 0 (tested)."""
        import jax

        from ..autograd.engine import no_grad
        from ..core.rng import next_key
        from ..distributed.resilience import RetryPolicy
        from ..nn.layer import bind_state, functional_state

        self.eval()
        cfg = self.config
        if cfg.moe_experts > 0:
            raise ValueError("chunked compile supports dense blocks only")
        ids_raw = input_ids.value if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        b, s = ids_raw.shape
        total = s + max_new_tokens
        nh, hd, nl = cfg.num_heads, cfg.head_dim, cfg.num_layers
        state = functional_state(self)
        dt = state["params"]["gpt.wte.weight"].dtype
        temp, tk = float(temperature), top_k
        key_raw = key.value if isinstance(key, Tensor) else key
        if key_raw is None:
            key_raw = next_key()
        # transport errors only: deterministic compile failures (JAX
        # RuntimeErrors — including the reproducible 1.3B broken-pipe
        # this path works around by SHRINKING programs) propagate
        # immediately instead of burning 3 multi-minute attempts
        retry = RetryPolicy(max_attempts=3, base_delay_s=0.5,
                            retry_on=(ConnectionError, OSError))

        def raw(t):
            return t.value if isinstance(t, Tensor) else t

        blk0 = self.gpt.h[0]
        # params AND buffers: converted layers (WeightOnlyInt8Linear)
        # carry their quantized weights as buffers — binding params
        # alone would run every layer on blk0's closed-over buffers
        pnames = [n for n, _ in blk0.named_parameters()]
        bnames = [n for n, _ in blk0.named_buffers()]
        n_p = len(pnames)

        def layer_vals(blk):
            ps = dict(blk.named_parameters())
            bs = dict(blk.named_buffers())
            return ([raw(ps[n]) for n in pnames] +
                    [None if bs[n] is None else raw(bs[n])
                     for n in bnames])

        layer_params = [layer_vals(blk) for blk in self.gpt.h]

        # jit objects are cached on the model (state/params flow in as
        # ARGUMENTS): repeated calls — e.g. bench timing windows — hit
        # the per-shape compile cache instead of rebuilding the jits
        # and recompiling every window through the very transport this
        # path exists to spare
        cache = getattr(self, "_chunked_jit_cache", None)
        if cache is None:
            cache = self._chunked_jit_cache = {}
        # the parameter-name tuple keys STRUCTURE: an in-place layer
        # swap (e.g. convert_to_weight_only_int8) changes the names,
        # so the cached closure over the old structure is not reused
        # against new-layout params (the r5 stale-pack-cache lesson)
        sig = (temp, tk, tuple(pnames), tuple(bnames))
        if sig not in cache:
            # same single-device-trace guard as _generate_jit: a live
            # fleet group's activation constraints must not reach these
            # per-block programs
            from ..distributed.mp_layers import no_sharding_constraints

            def embed_fn(st, ids, pos0):
                with bind_state(self, st), no_grad(), \
                        no_sharding_constraints():
                    pos = pos0 + jnp.arange(ids.shape[1],
                                            dtype=jnp.int32)[None]
                    pos = jnp.broadcast_to(pos, ids.shape)
                    x = self.gpt.wte(Tensor(ids)) + \
                        self.gpt.wpe(Tensor(pos))
                return raw(x)

            def block_fn(x, k_buf, v_buf, pos, *vals):
                st = {"params": dict(zip(pnames, vals[:n_p])),
                      "buffers": dict(zip(bnames, vals[n_p:]))}
                with bind_state(blk0, st), no_grad(), \
                        no_sharding_constraints():
                    out, nc = blk0(Tensor(x),
                                   StaticKVCache(k_buf, v_buf, pos),
                                   use_cache=True)
                return raw(out), raw(nc.k), raw(nc.v)

            def head_fn(st, x):
                with bind_state(self, st), no_grad(), \
                        no_sharding_constraints():
                    lg = self.logits(self.gpt.ln_f(Tensor(x)))
                return raw(lg)[:, -1]

            def sample_fn(last, k):
                from ..nn.decode import sample_token
                return sample_token(last, temp, tk, k)

            cache[sig] = tuple(
                jax.jit(f) for f in (embed_fn, block_fn, head_fn,
                                     sample_fn))
        embed_j, block_j, head_j, sample_j = cache[sig]
        kvs = [(jnp.zeros((b, total, nh, hd), dt),
                jnp.zeros((b, total, nh, hd), dt)) for _ in range(nl)]

        def run_stack(ids, pos):
            x = retry.call(embed_j, state, ids, pos,
                           site="jit.compile.embed")
            for i in range(nl):
                x, kb, vb = retry.call(
                    block_j, x, kvs[i][0], kvs[i][1], pos,
                    *layer_params[i], site="jit.compile.block")
                kvs[i] = (kb, vb)
            return retry.call(head_j, state, x, site="jit.compile.head")

        pos = jnp.asarray(0, jnp.int32)
        last = run_stack(ids_raw, pos)
        pos = pos + s
        nxt, key_raw = sample_j(last, key_raw)
        out = [ids_raw, nxt[:, None]]
        for _ in range(max_new_tokens - 1):
            last = run_stack(nxt[:, None], pos)
            pos = pos + 1
            nxt, key_raw = sample_j(last, key_raw)
            out.append(nxt[:, None])
        return Tensor(jnp.concatenate(out, axis=1))


# -- checkpoint-state helpers (r24 weight hot-swap) -------------------------

def checkpoint_state(model: Layer) -> dict:
    """The model's full weight tree as plain host numpy arrays keyed by
    structured name — the form ``ResilientCheckpointManager`` saves and
    a swap/restore applies back through ``set_state_dict``. Buffers are
    included (converted layers hold int8 weights there), so a restored
    tree is the COMPLETE serving state, never a partial apply."""
    import numpy as np
    return {name: np.asarray(t.value)
            for name, t in model.state_dict(
                include_non_persistable_buffer=True).items()}


def perturbed_state(state: dict, scale: float = 1e-3,
                    seed: int = 0) -> dict:
    """A deterministic variant of ``state`` with every float leaf
    nudged by ``scale`` — how tests/benches/chaos manufacture a "new
    checkpoint" that is structurally identical but produces different
    logits (so a hot-swap's generation isolation is observable) without
    training anything. Integer/bool leaves pass through untouched."""
    import numpy as np
    rng = np.random.default_rng(seed)
    out = {}
    for name, arr in state.items():
        arr = np.asarray(arr)
        if np.issubdtype(arr.dtype, np.floating):
            out[name] = (arr + scale * rng.standard_normal(
                arr.shape).astype(arr.dtype)).astype(arr.dtype)
        else:
            out[name] = arr
    return out
