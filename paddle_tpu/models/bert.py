"""BERT encoder family (masked-LM + classification heads).

Reference parity: the BERT configs driven by the reference's static-graph
pretrain benchmarks (BASELINE.md config #2) and its dygraph_to_static
test_bert.py model. Built on the shared TransformerEncoder stack so it
exercises the same attention/layernorm kernels as GPT.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .. import dispatch
from ..nn.common import Dropout, Embedding, Linear
from ..nn.layer import Layer
from ..nn.norm import LayerNorm
from ..nn.transformer import TransformerEncoder, TransformerEncoderLayer

F = dispatch.wrapped_ops


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12


def bert_tiny(**kw):
    return BertConfig(vocab_size=1024, hidden_size=128,
                      num_hidden_layers=2, num_attention_heads=2,
                      intermediate_size=256, max_position_embeddings=128,
                      hidden_dropout_prob=0.0,
                      attention_probs_dropout_prob=0.0, **kw)


def bert_base(**kw):
    return BertConfig(**kw)


def bert_large(**kw):
    return BertConfig(hidden_size=1024, num_hidden_layers=24,
                      num_attention_heads=16, intermediate_size=4096, **kw)


class BertEmbeddings(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        c = config
        self.word_embeddings = Embedding(c.vocab_size, c.hidden_size)
        self.position_embeddings = Embedding(c.max_position_embeddings,
                                             c.hidden_size)
        self.token_type_embeddings = Embedding(c.type_vocab_size,
                                               c.hidden_size)
        self.layer_norm = LayerNorm(c.hidden_size,
                                    epsilon=c.layer_norm_eps)
        self.dropout = Dropout(c.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = F["expand"](F["unsqueeze"](
                F["arange"](s, dtype="int32"), 0), (b, s))
        if token_type_ids is None:
            token_type_ids = F["zeros"]((b, s), dtype="int32")
        emb = (self.word_embeddings(input_ids) +
               self.position_embeddings(position_ids) +
               self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(emb))


class BertModel(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        c = config
        self.embeddings = BertEmbeddings(c)
        enc_layer = TransformerEncoderLayer(
            c.hidden_size, c.num_attention_heads, c.intermediate_size,
            dropout=c.hidden_dropout_prob, activation=c.hidden_act,
            attn_dropout=c.attention_probs_dropout_prob)
        self.encoder = TransformerEncoder(enc_layer, c.num_hidden_layers)
        self.pooler = Linear(c.hidden_size, c.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        if attention_mask is not None and attention_mask.ndim == 2:
            # [B, S] padding mask -> additive [B, 1, 1, S]
            m = F["unsqueeze"](F["unsqueeze"](attention_mask, 1), 1)
            attention_mask = (1.0 - F["cast"](m, "float32")) * -1e9
        seq_out = self.encoder(x, src_mask=attention_mask)
        pooled = F["tanh"](self.pooler(seq_out[:, 0]))
        return seq_out, pooled


class BertForPretraining(Layer):
    """MLM + NSP heads (the reference pretrain benchmark config)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        c = config
        self.bert = BertModel(c)
        self.mlm_transform = Linear(c.hidden_size, c.hidden_size)
        self.mlm_norm = LayerNorm(c.hidden_size, epsilon=c.layer_norm_eps)
        self.nsp_head = Linear(c.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, masked_positions=None,
                labels=None, next_sentence_labels=None,
                attention_mask=None):
        seq_out, pooled = self.bert(input_ids, token_type_ids,
                                    attention_mask=attention_mask)
        if masked_positions is not None:
            # The reference pretrain data format fixes
            # max_predictions_per_seq masked slots per sequence: run the
            # MLM transform + 30k-vocab head on those K positions only
            # (the full-sequence head spends ~85% of its matmul + CE on
            # positions that carry no label). ``labels`` may be the
            # gathered [B, K] ids or the full [B, S] label tensor.
            seq_out = F["take_along_axis"](
                seq_out, F["unsqueeze"](masked_positions, -1), 1)
            k = masked_positions.shape[1]
            if labels is not None and k != input_ids.shape[1] and \
                    tuple(labels.shape) == tuple(input_ids.shape):
                # full [B, S] labels: gather to the masked slots. When
                # K == S the shapes are ambiguous and labels are taken
                # as ALREADY gathered (the reference masked_lm_ids
                # form) — never double-gather.
                labels = F["take_along_axis"](labels, masked_positions, 1)
        h = self.mlm_norm(F["gelu"](self.mlm_transform(seq_out)))
        mlm_logits = F["matmul"](
            h, self.bert.embeddings.word_embeddings.weight,
            transpose_y=True)
        nsp_logits = self.nsp_head(pooled)
        if labels is None:
            return mlm_logits, nsp_logits
        mlm_loss = F["cross_entropy"](mlm_logits, labels,
                                      ignore_index=-100)
        loss = mlm_loss
        if next_sentence_labels is not None:
            loss = loss + F["cross_entropy"](nsp_logits,
                                             next_sentence_labels)
        return loss


class BertForSequenceClassification(Layer):
    def __init__(self, config: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.classifier = Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        _, pooled = self.bert(input_ids, token_type_ids,
                              attention_mask=attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is None:
            return logits
        return F["cross_entropy"](logits, labels)
