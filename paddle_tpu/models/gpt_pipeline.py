"""Pipeline-parallel GPT training step.

The reference trains GPT-class models with static pipeline parallelism
(PipelineOptimizer fluid/optimizer.py:4134 splitting the program into
per-stage sections + SectionWorker microbatch schedules
section_worker.cc:130-180). TPU-native: GPT blocks are uniform, so the
whole stack is ONE stacked [n_layers, ...] params pytree sharded over the
"pp" mesh axis; inside shard_map each device scans its local blocks and
spmd_pipeline rotates microbatch activations around the pp ring. jax.grad
through the loop reverses the permutes (F-then-B). schedule="1f1b"
selects the true 1F1B schedule (spmd_pipeline_1f1b): O(pp) in-flight
activations independent of n_micro, matching section_worker.cc:144-180.

Embedding/head run replicated on every stage (cheap vs the blocks), which
also implements the reference's tied-embedding weight sync
(pp_layers.py:180-188) for free: there is only one copy.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Optional, Tuple

import jax
from ..compat import axis_size as _compat_axis_size
import jax.numpy as jnp
import numpy as np
from ..compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..autograd.engine import no_grad
from ..nn.layer import bind_state, functional_state
from ..tensor import Tensor
from ..distributed.pp import spmd_pipeline
from .gpt import GPTConfig, GPTForCausalLM


def _split_block_params(params: Dict[str, jax.Array], num_layers: int
                        ) -> Tuple[Dict[str, jax.Array],
                                   Dict[str, jax.Array]]:
    """Separate per-block params (stacked over a leading layer dim) from
    the shared embedding/head/final-norm params."""
    block_suffixes = sorted({k.split(".", 3)[3]
                             for k in params if k.startswith("gpt.h.")})
    stacked = {}
    for suffix in block_suffixes:
        leaves = [params[f"gpt.h.{i}.{suffix}"] for i in range(num_layers)]
        if isinstance(leaves[0], jax.ShapeDtypeStruct):  # abstract mode
            stacked[suffix] = jax.ShapeDtypeStruct(
                (num_layers,) + tuple(leaves[0].shape), leaves[0].dtype)
        else:
            stacked[suffix] = jnp.stack(leaves)
    shared = {k: v for k, v in params.items() if not k.startswith("gpt.h.")}
    return stacked, shared


def _param_pspecs(model) -> Dict[str, P]:
    """Tensor-parallel PartitionSpec per param name (P() when dense)."""
    return {n: (getattr(p, "pspec", None) or P())
            for n, p in model.named_parameters()}


def _merge_block_params(stacked: Dict[str, jax.Array],
                        shared: Dict[str, jax.Array], num_layers: int
                        ) -> Dict[str, jax.Array]:
    out = dict(shared)
    for suffix, v in stacked.items():
        for i in range(num_layers):
            out[f"gpt.h.{i}.{suffix}"] = v[i]
    return out


class GPTPipelineTrainStep:
    """shard_map(pp × dp) train step for GPTForCausalLM.

    Two modes:
    - standalone (default): builds its own (pp, dp) mesh, everything
      inside shard_map is fully manual.
    - hybrid (``hcg=`` the fleet HybridCommunicateGroup): runs on the ONE
      global mesh with manual={"pp"} only — tensor parallel (mp) and
      sequence parallel (sep) ride GSPMD constraints inside each stage,
      the batch shards over dp×sharding, and optimizer slots ZeRO-shard
      over ``zero_axis``. This is the reference's hardest composition
      (sharding_optimizer.py:968 _build_groups pp×mp×sharding interplay)
      expressed as one SPMD program.
    """

    def __init__(self, config: GPTConfig, optimizer, pp: int, dp: int = 1,
                 n_micro: int = 2, devices=None, remat: bool = False,
                 seed: int = 0, schedule: str = "fthenb", hcg=None,
                 zero_axis: Optional[str] = None, abstract: bool = False):
        assert config.num_layers % pp == 0, "layers must divide pp"
        assert config.dropout == 0.0 and config.attn_dropout == 0.0, \
            "pipeline step requires dropout=0 (rng is not plumbed per-stage)"
        self.config = config
        self.optimizer = optimizer
        self.n_micro = n_micro
        self.abstract = abstract
        import contextlib
        import paddle_tpu as pt
        from ..nn.initializer import abstract_init
        pt.seed(seed)
        # abstract: params are ShapeDtypeStructs (nothing materializes) so
        # multi-billion-param configs can be AOT-lowered against a target
        # topology (tools/scale_proof.py) without host/device memory.
        with (abstract_init() if abstract else contextlib.nullcontext()):
            self.model = GPTForCausalLM(config)
        self.model.eval()  # dropout off; training math identical
        self.hybrid = hcg is not None
        if self.hybrid:
            self.mesh = hcg.mesh
            assert self.mesh.shape["pp"] == pp, \
                (self.mesh.shape, pp)
        else:
            devices = list(devices if devices is not None
                           else jax.devices())
            dev = np.asarray(devices[:pp * dp]).reshape(pp, dp)
            self.mesh = Mesh(dev, ("pp", "dp"))
        state = functional_state(self.model)
        stacked, shared = _split_block_params(state["params"],
                                              config.num_layers)

        def _place(v, spec):
            sh = NamedSharding(self.mesh, spec)
            if self.abstract:
                return jax.ShapeDtypeStruct(tuple(v.shape), v.dtype,
                                            sharding=sh)
            return jax.device_put(v, sh)
        self._place = _place
        if self.hybrid:
            pspecs = _param_pspecs(self.model)
            # every layer's suffix carries the same TP spec; index layer 0
            stacked_specs = {
                suf: P("pp", *pspecs[f"gpt.h.0.{suf}"])
                for suf in stacked}
            # embed/head/final-norm run replicated on every stage (by
            # design — tied-embedding sync for free); also, a
            # vocab-sharded embedding gather inside a manual-pp subgroup
            # trips XLA's SPMD partitioner, so mp shards block matmuls
            # only.
            shared_specs = {n: P() for n in shared}
            self.stacked = {suf: _place(v, stacked_specs[suf])
                            for suf, v in stacked.items()}
            self.shared = {n: _place(v, shared_specs[n])
                           for n, v in shared.items()}
            self._data_axes = tuple(
                ax for ax in ("dp", "sharding")
                if self.mesh.shape.get(ax, 1) > 1)
        else:
            self.stacked = {suf: _place(v, P("pp"))
                            for suf, v in stacked.items()}
            self.shared = {n: _place(v, P()) for n, v in shared.items()}
            self._data_axes = ("dp",)
        params = {"stacked": self.stacked, "shared": self.shared}
        # slots inherit their param's sharding (stacked slots ride pp)
        if self.abstract:
            self.opt_state = self._abstract_opt_init(params)
        else:
            self.opt_state = optimizer.init(params)
        if self.hybrid and zero_axis and \
                self.mesh.shape.get(zero_axis, 1) > 1:
            self._zero_shard_slots(zero_axis)

        assert schedule in ("fthenb", "1f1b"), schedule
        self.schedule = schedule
        self._step = (self._build(remat) if schedule == "fthenb"
                      else self._build_1f1b(remat))

    def _abstract_opt_init(self, params):
        """optimizer.init without materializing: eval_shape the slot tree,
        then give every slot its param's sharding (shape-matched leaves)
        or replication (scalars/step counters) — the same placements the
        concrete Optimizer.init assigns via place_like."""
        opt_shapes = jax.eval_shape(self.optimizer.init, params)
        flat_p, pdef = jax.tree_util.tree_flatten(params)
        flat_slots = pdef.flatten_up_to(opt_shapes["slots"])

        def attach(p, slot_tree):
            def leaf(s):
                sh = (p.sharding if tuple(s.shape) == tuple(p.shape)
                      else NamedSharding(self.mesh, P()))
                return jax.ShapeDtypeStruct(tuple(s.shape), s.dtype,
                                            sharding=sh)
            return jax.tree_util.tree_map(leaf, slot_tree)

        slots = jax.tree_util.tree_unflatten(
            pdef, [attach(p, s) for p, s in zip(flat_p, flat_slots)])
        step = jax.ShapeDtypeStruct((), jnp.int32,
                                    sharding=NamedSharding(self.mesh, P()))
        return {"slots": slots, "step": step}

    def _zero_shard_slots(self, axis: str) -> None:
        """ZeRO-1: moment slots of the stacked block params shard over
        `axis` on their first free, divisible dim (reference:
        sharding_optimizer.py optimizer-state sharding; the param itself
        stays pp/mp-sharded). Shared embedding/head slots stay replicated:
        they are small, and a sharded slot's spec propagates back onto the
        embedding-gather operand, which XLA's gather partitioner cannot
        handle under manual-pp subgroups."""
        deg = self.mesh.shape[axis]

        def reshard(slot):
            if not isinstance(slot, (jax.Array, jax.ShapeDtypeStruct)) \
                    or slot.ndim == 0:
                return slot
            spec = list(getattr(slot.sharding, "spec", P()) or [])
            spec += [None] * (slot.ndim - len(spec))
            for d in range(slot.ndim):
                if spec[d] is None and slot.shape[d] % deg == 0 \
                        and slot.shape[d] >= deg:
                    spec[d] = axis
                    return self._place(slot, P(*spec))
            return slot

        self.opt_state["slots"]["stacked"] = jax.tree_util.tree_map(
            reshard, self.opt_state["slots"]["stacked"])

    # -- functional pieces ----------------------------------------------------

    def _zigzag_sep(self) -> int:
        """sep degree when the config runs the balanced zigzag ring over
        a sep axis in this mesh; 0 otherwise."""
        sep = dict(self.mesh.shape).get("sep", 1)
        if self.config.seq_parallel_mode != "zigzag" or sep <= 1:
            return 0
        return sep

    def _embed(self, shared, ids):
        model = self.model
        b, s = ids.shape
        sep = self._zigzag_sep()
        import jax.numpy as jnp
        if sep:
            # Zigzag layout from the very first op: chunk-reorder the
            # int ids (split+concat — a sequence-axis GATHER inside the
            # manual-pp region trips the TPU SPMD partitioner), and
            # feed the permuted positions as position ids. The whole
            # block stack then runs in zigzag order (positionwise ops
            # are invariant; attention runs the balanced ring);
            # _head_loss un-permutes before the next-token shift.
            from ..distributed.sp import (zigzag_permutation,
                                          zigzag_reorder)
            ids = zigzag_reorder(ids, sep, axis=1)
            perm, _ = zigzag_permutation(s, sep)
        with bind_state(model, {"params": shared, "buffers": {}}), \
                no_grad():
            import paddle_tpu.dispatch as dispatch
            F = dispatch.wrapped_ops
            if sep:
                pos = jnp.broadcast_to(
                    jnp.asarray(perm, jnp.int32)[None, :], (b, s))
                pos = Tensor(pos)
            else:
                pos = F["arange"](s, dtype="int32")
                pos = F["expand"](F["unsqueeze"](pos, 0), (b, s))
            x = model.gpt.wte(Tensor(ids)) + model.gpt.wpe(pos)
            return x.value

    def _head_loss(self, shared, hidden, labels):
        model = self.model
        sep = self._zigzag_sep()
        if sep:
            # Restore the public order before the next-token shift —
            # chunk-level split+concat (shard-aligned slices lower to
            # collective-permutes; a sharded-S gather trips the TPU
            # SPMD partitioner).
            from ..distributed.sp import zigzag_reorder
            hidden = zigzag_reorder(hidden, sep, axis=1, inverse=True)
        with bind_state(model, {"params": shared, "buffers": {}}), \
                no_grad():
            h = model.gpt.ln_f(Tensor(hidden))
            if model.config.loss_chunk_size:
                # chunked CE: the [mb, S, vocab] logits never materialize
                # (same path as GPTForCausalLM.forward)
                loss = model._chunked_lm_loss(
                    h, Tensor(labels), model.config.loss_chunk_size)
                return loss.value if isinstance(loss, Tensor) else loss
            logits = model.logits(h)
            import paddle_tpu.dispatch as dispatch
            F = dispatch.wrapped_ops
            loss = F["mean"](model.loss_fn(logits[:, :-1],
                                           Tensor(labels)[:, 1:]))
            return loss.value

    def _block_apply(self, blk_params, x):
        """Apply ONE block given its unstacked param dict."""
        block = self.model.gpt.h[0]
        named = {k: v for k, v in blk_params.items()}
        with bind_state(block, {"params": named, "buffers": {}}), \
                no_grad():
            return block(Tensor(x)).value

    def _build(self, remat: bool):
        n_micro = self.n_micro
        layers_per_stage = self.config.num_layers // self.mesh.shape["pp"]
        block_apply = self._block_apply
        embed = self._embed
        head_loss = self._head_loss
        optimizer = self.optimizer
        mesh = self.mesh

        def stage_fn(blocks_local, x):
            # blocks_local: dict of [L/pp, ...]; scan across local layers
            def body(h, blk):
                return block_apply(blk, h), None
            h, _ = jax.lax.scan(body, x, blocks_local)
            return h

        from ..core.offload import remat_policy
        with self._remat_scope():
            sfn = jax.checkpoint(stage_fn, policy=remat_policy()) \
                if remat else stage_fn
        hybrid = self.hybrid
        data_axes = self._data_axes

        def loss_fn(stacked, shared, ids, labels):
            def inner(stacked_l, shared_l, ids_l, labels_l):
                # stacked_l: [L/pp, ...] local blocks; ids_l: dp-local
                # batch (standalone) or the global batch with auto
                # dp/sharding sharding (hybrid)
                if hybrid:
                    # keep the embedding/CE gathers' indices replicated
                    # (XLA's gather partitioner mishandles sharded
                    # indices under manual-pp subgroups), then push the
                    # activations onto the data axes
                    ids_l = jax.lax.with_sharding_constraint(ids_l, P())
                    labels_l = jax.lax.with_sharding_constraint(
                        labels_l, P())
                x = embed(shared_l, ids_l)  # [mb*nm, s, h]
                if hybrid and data_axes:
                    x = jax.lax.with_sharding_constraint(
                        x, P(data_axes if len(data_axes) > 1
                             else data_axes[0]))
                b = x.shape[0]
                mb = b // n_micro
                x_micro = x.reshape(n_micro, mb, *x.shape[1:])
                outs = spmd_pipeline(lambda bp, xm: sfn(bp, xm),
                                     stacked_l, x_micro, axis_name="pp")
                hidden = outs.reshape(b, *x.shape[1:])
                loss = head_loss(shared_l, hidden, labels_l)
                # only the last stage's loss is real; psum broadcasts it
                n_stages = _compat_axis_size("pp")
                stage = jax.lax.axis_index("pp")
                loss = jnp.where(stage == n_stages - 1, loss, 0.0)
                loss = jax.lax.psum(loss, "pp")
                if not hybrid:  # hybrid: dp is auto; mean is global
                    loss = jax.lax.pmean(loss, "dp")
                return loss

            data_spec = P() if hybrid else P("dp")
            smapped = shard_map(
                inner, mesh=mesh,
                in_specs=(P("pp"), P(), data_spec, data_spec),
                out_specs=P(), check_vma=False,
                **({"axis_names": frozenset({"pp"})} if hybrid else {}))
            return smapped(stacked, shared, ids, labels)

        def step_impl(params, opt_state, lr, ids, labels):
            from ..distributed.mp_layers import no_sharding_constraints
            import contextlib
            guard = (contextlib.nullcontext() if hybrid
                     else no_sharding_constraints())
            with guard:
                loss, grads = jax.value_and_grad(
                    lambda p: loss_fn(p["stacked"], p["shared"], ids,
                                      labels))(params)
            # check_vma=False skips the automatic replication-sum for
            # grads of replicated/pp-sharded inputs; psums were made
            # explicit in loss_fn, and GSPMD resolves grad shardings here.
            new_params, new_opt = optimizer.apply_gradients(
                params, grads, opt_state, lr=lr)
            return new_params, new_opt, loss

        return jax.jit(step_impl, donate_argnums=(0, 1))

    def _build_1f1b(self, remat: bool):
        """Memory-bounded 1F1B schedule with manual backward composition
        (reference: section_worker.cc:144-180); activations in flight are
        O(pp) instead of O(n_micro)."""
        from ..distributed.pp import spmd_pipeline_1f1b

        n_micro = self.n_micro
        block_apply = self._block_apply
        embed = self._embed
        head_loss = self._head_loss
        optimizer = self.optimizer
        mesh = self.mesh

        def stage_fn(blocks_local, x):
            def body(h, blk):
                return block_apply(blk, h), None
            h, _ = jax.lax.scan(body, x, blocks_local)
            return h

        hybrid = self.hybrid

        def inner(stacked_l, shared_l, ids_l, labels_l):
            b, s = ids_l.shape
            mb = b // n_micro
            ids_m = ids_l.reshape(n_micro, mb, s)
            labels_m = labels_l.reshape(n_micro, mb, s)

            def first_fn(sh, mb_idx):
                return embed(sh, jax.lax.dynamic_index_in_dim(
                    ids_m, mb_idx, keepdims=False))

            def last_fn(sh, y, mb_idx):
                lbl = jax.lax.dynamic_index_in_dim(labels_m, mb_idx,
                                                   keepdims=False)
                return head_loss(sh, y, lbl) / n_micro

            loss_sum, d_stacked, d_shared = spmd_pipeline_1f1b(
                stage_fn, stacked_l, shared_l, first_fn, last_fn,
                n_micro, axis_name="pp", remat=remat)
            loss = jax.lax.psum(loss_sum, "pp")
            d_shared = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g, "pp"), d_shared)
            if not hybrid:  # hybrid: dp/sharding are auto; GSPMD sums
                loss = jax.lax.pmean(loss, "dp")
                d_stacked = jax.tree_util.tree_map(
                    lambda g: jax.lax.pmean(g, "dp"), d_stacked)
                d_shared = jax.tree_util.tree_map(
                    lambda g: jax.lax.pmean(g, "dp"), d_shared)
            return loss, d_stacked, d_shared

        def step_impl(params, opt_state, lr, ids, labels):
            from ..distributed.mp_layers import no_sharding_constraints
            import contextlib
            guard = (contextlib.nullcontext() if hybrid
                     else no_sharding_constraints())
            data_spec = P() if hybrid else P("dp")
            with guard:
                smapped = shard_map(
                    inner, mesh=mesh,
                    in_specs=(P("pp"), P(), data_spec, data_spec),
                    out_specs=(P(), P("pp"), P()), check_vma=False,
                    **({"axis_names": frozenset({"pp"})} if hybrid
                       else {}))
                loss, d_stacked, d_shared = smapped(
                    params["stacked"], params["shared"], ids, labels)
            grads = {"stacked": d_stacked, "shared": d_shared}
            new_params, new_opt = optimizer.apply_gradients(
                params, grads, opt_state, lr=lr)
            return new_params, new_opt, loss

        return jax.jit(step_impl, donate_argnums=(0, 1))

    def _batch_pspec(self) -> P:
        """PartitionSpec for the [batch, seq] token arrays (one source of
        truth for __call__ and lower())."""
        if self.hybrid and self._data_axes:
            return P(self._data_axes if len(self._data_axes) > 1
                     else self._data_axes[0])
        if not self.hybrid:
            return P("dp")
        return P()

    def lower(self, batch_size: int, seq_len: int):
        """AOT-lower one train step with abstract arguments (usable in
        both modes; the point of abstract=True). Returns the jax Lowered —
        .compile() against the mesh's (possibly compile-only) topology
        yields per-device memory analysis without running anything."""
        ids = jax.ShapeDtypeStruct(
            (batch_size, seq_len), jnp.int32,
            sharding=NamedSharding(self.mesh, self._batch_pspec()))
        lr = jax.ShapeDtypeStruct(
            (), jnp.float32, sharding=NamedSharding(self.mesh, P()))
        params = {"stacked": self.stacked, "shared": self.shared}
        with self._remat_scope(), self.mesh:
            return self._step.lower(params, self.opt_state, lr, ids, ids)

    def _remat_scope(self):
        """The model's selective-remat selection, scoped (GPTModel
        captures it per-model; the pipeline path never runs
        GPTModel.forward, so the override must wrap every point that
        consults core.offload at build or trace time: remat_policy()
        in _build, spmd_pipeline_1f1b's policy evaluation, and the
        flash kernel's name_activation tagging inside the step trace)."""
        import contextlib
        names = self.model.gpt._remat_names
        if names is None:
            return contextlib.nullcontext()
        from ..core.offload import override_remat_saved_names
        return override_remat_saved_names(names)

    def __call__(self, ids, labels) -> jax.Array:
        assert not self.abstract, \
            "abstract=True builds a compile-only step: use lower()"
        params = {"stacked": self.stacked, "shared": self.shared}
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        ids, labels = jnp.asarray(ids), jnp.asarray(labels)
        if self.hybrid and self._data_axes:
            # batch dim over dp×sharding (the pp split is handled by the
            # manual shard_map in_specs)
            bspec = NamedSharding(self.mesh, self._batch_pspec())
            ids = jax.device_put(ids, bspec)
            labels = jax.device_put(labels, bspec)
        # the mesh context lets bare-PartitionSpec sharding constraints
        # inside the partial-manual program resolve on older jax (newer
        # jax resolves them against the abstract mesh without it)
        with self._remat_scope(), self.mesh:
            params, self.opt_state, loss = self._step(
                params, self.opt_state, lr, ids, labels)
        self.stacked = params["stacked"]
        self.shared = params["shared"]
        return loss

    def merged_params(self) -> Dict[str, jax.Array]:
        return _merge_block_params(self.stacked, self.shared,
                                   self.config.num_layers)
