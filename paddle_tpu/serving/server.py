"""Threaded socket front-end over the continuous-batching engine.

Newline-JSON protocol (one JSON object per line, both directions):

    -> {"op": "generate", "prompt": [1, 2, 3], "max_new_tokens": 8,
        "priority": "interactive", "stream": true, "eos": 7}
    <- {"rid": 0, "token": 17, "done": false}          # per token (stream)
    <- {"rid": 0, "done": true, "tokens": [...], "stats": {...}}
    -> {"op": "health"}
    <- {"status": "ok", "active": 1, "queued": 0, "free_pages": 9, ...}
    -> {"op": "stats"}     # metrics snapshot (JSON)
    -> {"op": "metrics"}   # Prometheus text page (in "text")
    -> {"op": "drain"}     # stop admitting, finish in-flight, close

Typed failures are structured replies, never hangs: an overloaded
queue answers ``{"error": "ServerOverloaded", "retry_after_ms": ...}``
(serving/scheduler.py), a prefill whose retries exhausted answers
``{"error": "PrefillFailed"}``, a drain answers in-flight requests
normally and rejects new ones with ``{"error": "ServerDraining"}``.

Threading model: the ENGINE THREAD exclusively owns the engine (it is
not thread-safe) — connection threads parse requests and hand them
over through an inbox queue; per-token streaming flows back through
per-request outbox queues, so a slow client can never stall the decode
step. Graceful drain: stop admitting, finish in-flight work, return
every page, `engine.close()` (which asserts ``check_no_leak``).

Fault sites (distributed/fault_inject.py): ``serving.request`` fires
in the connection thread per request (clients get a retryable typed
error); ``serving.prefill`` fires inside engine admission and is
retried per the ``serving.prefill`` resilience policy.

Run it: ``python -m paddle_tpu.serving.server --model gpt_125m``.
Speculative decoding: ``--speculate 4`` (n-gram/prompt-lookup draft,
no second model) or ``--speculate 4 --draft-model gpt_tiny`` (a small
model drafts; its greedy guesses are verified in one multi-token
forward, so greedy outputs stay bit-identical to the vanilla engine
while each accepted draft amortizes the weight/KV stream). Per-request
acceptance rate and tokens-per-step land in the ``stats`` reply and
the Prometheus ``metrics`` page.

Reference analog: the C serving API / AnalysisPredictor server loop
(SURVEY §1 rows 7/12), TPU-native over one jitted decode step.
"""

from __future__ import annotations

import json
import queue as queue_mod
import socket
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from .metrics import ServingMetrics
from .prefix_cache import PrefixCache
from .scheduler import Priority, ServerOverloaded, SLOScheduler

__all__ = ["ServingServer", "client_request"]

_PRIORITIES = {"batch": Priority.BATCH, "normal": Priority.NORMAL,
               "interactive": Priority.INTERACTIVE}


class _Pending:
    """Engine-side record of one in-flight client request."""

    __slots__ = ("outbox", "stream")

    def __init__(self, stream: bool):
        self.outbox: "queue_mod.Queue[Optional[Dict]]" = queue_mod.Queue()
        self.stream = stream


class ServingServer:
    """In-process serving front-end (tests construct it directly; the
    CLI entry below wraps it).

    ``engine_kwargs`` pass through to `create_decode_engine`
    (num_slots, page_size, num_pages, ...). ``prefix_cache=True``
    builds a `PrefixCache` sized to the engine's page_size;
    ``scheduler=None`` defaults to an `SLOScheduler` with stock
    SLOConfig. ``prefill_retry=None`` resolves the ``serving.prefill``
    site policy from distributed/resilience.py."""

    def __init__(self, model, host: str = "127.0.0.1", port: int = 0,
                 scheduler=None, prefix_cache: bool = True,
                 metrics: Optional[ServingMetrics] = None,
                 prefill_retry="site", max_new_tokens_cap: int = 512,
                 poll_interval_s: float = 0.02,
                 max_engine_errors: int = 32, **engine_kwargs):
        from ..inference import create_decode_engine
        from ..distributed.resilience import get_retry_policy

        self.host = host
        self._requested_port = port
        self.scheduler = scheduler if scheduler is not None \
            else SLOScheduler()
        self.metrics = metrics if metrics is not None else ServingMetrics()
        page_size = int(engine_kwargs.get("page_size", 64))
        self.prefix_cache = PrefixCache(page_size) if prefix_cache \
            else None
        if prefill_retry == "site":
            prefill_retry = get_retry_policy("serving.prefill")
        self.engine = create_decode_engine(
            model, scheduler=self.scheduler,
            prefix_cache=self.prefix_cache,
            prefill_retry=prefill_retry,
            on_complete=self._on_complete, **engine_kwargs)
        self.max_new_tokens_cap = int(max_new_tokens_cap)
        self.poll_interval_s = float(poll_interval_s)
        self.max_engine_errors = int(max_engine_errors)
        self._consec_errors = 0

        self._inbox: "queue_mod.Queue[tuple]" = queue_mod.Queue()
        self._admission_lock = threading.Lock()
        self._pending: Dict[int, _Pending] = {}  # engine thread only
        self._wake = threading.Event()
        self._engine_done = threading.Event()
        self._draining = False
        self._stopping = False
        self._started = False
        self._listen_sock: Optional[socket.socket] = None
        self._threads = []
        self._conn_threads = []
        self._conns = []
        self._conns_lock = threading.Lock()
        self._t0 = time.monotonic()
        self.port: Optional[int] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> int:
        """Bind, listen, and start the accept + engine threads.
        Returns the bound port (OS-assigned when constructed with
        port=0)."""
        if self._started:
            return self.port
        self._listen_sock = socket.socket(socket.AF_INET,
                                          socket.SOCK_STREAM)
        self._listen_sock.setsockopt(socket.SOL_SOCKET,
                                     socket.SO_REUSEADDR, 1)
        self._listen_sock.bind((self.host, self._requested_port))
        self._listen_sock.listen(64)
        self.port = self._listen_sock.getsockname()[1]
        self._started = True
        for name, fn in (("engine", self._engine_loop),
                         ("accept", self._accept_loop)):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"pt-serving-{name}")
            t.start()
            self._threads.append(t)
        return self.port

    def drain(self) -> None:
        """Stop admitting new requests; in-flight and already-queued
        work finishes normally."""
        self._draining = True
        self._wake.set()

    def stop(self, timeout_s: float = 60.0) -> None:
        """Graceful shutdown: drain, finish in-flight, return pages
        (engine.close() asserts check_no_leak), close sockets."""
        self._draining = True
        self._stopping = True
        self._wake.set()
        for t in self._threads:
            if t is threading.current_thread():
                continue
            t.join(timeout=timeout_s)
        if self._listen_sock is not None:
            try:
                self._listen_sock.close()
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns)
            threads = list(self._conn_threads)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for t in threads:
            t.join(timeout=5.0)

    def __enter__(self) -> "ServingServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- engine thread -----------------------------------------------------

    def _engine_loop(self) -> None:
        eng = self.engine
        while True:
            self._drain_inbox()
            has_work = eng.num_queued or eng.num_active
            if has_work:
                try:
                    before = eng.num_queued + eng.num_active
                    eng.step()
                    after = eng.num_queued + eng.num_active
                    self._consec_errors = 0
                    if after and after == before and not eng.num_active:
                        # queued but nothing admissible and nothing
                        # decoding: don't hot-spin on the free list
                        time.sleep(self.poll_interval_s)
                except Exception:
                    # a failed prefill already unwound inside the
                    # engine (request requeued, or FAILED with a typed
                    # reply via on_complete) — the serving loop must
                    # outlive it either way. A PERSISTENT step failure
                    # (decode jit broken, pools consumed) must not
                    # wedge clients forever: past the consecutive-error
                    # cap, fail everything typed and stop admitting.
                    self.metrics.counter("engine_errors_total").add()
                    self._consec_errors += 1
                    if self._consec_errors >= self.max_engine_errors:
                        self._fail_engine()
                    time.sleep(self.poll_interval_s)
                continue
            if self._stopping and self._inbox.empty():
                try:
                    eng.close()
                finally:
                    # unblock any conn thread still waiting on a
                    # pending outbox (evicted replies already sent by
                    # close() -> on_complete)
                    for p in self._pending.values():
                        p.outbox.put(None)
                    self._pending.clear()
                    self._engine_done.set()
                return
            self._wake.wait(timeout=self.poll_interval_s)
            self._wake.clear()

    def _fail_engine(self) -> None:
        """Terminal engine failure (engine thread): every in-flight and
        inboxed client gets a typed EngineFailed reply, the engine's
        pages are torn down best-effort, and the server stops admitting
        (health keeps answering with status "draining")."""
        self._draining = True
        err = {"error": "EngineFailed",
               "reason": f"decode engine failed "
                         f"{self._consec_errors} consecutive steps; "
                         f"server stopped admitting"}
        try:
            self.engine.close()  # sends ServerEvicted via on_complete
        except Exception:
            pass
        for p in self._pending.values():
            p.outbox.put(dict(err))
            p.outbox.put(None)
        self._pending.clear()
        while True:
            try:
                _payload, p = self._inbox.get_nowait()
            except queue_mod.Empty:
                break
            p.outbox.put(dict(err))
            p.outbox.put(None)

    def _drain_inbox(self) -> None:
        while True:
            try:
                payload, pending = self._inbox.get_nowait()
            except queue_mod.Empty:
                return

            def on_token(rid, tok, done, _p=pending):
                if _p.stream:
                    _p.outbox.put({"rid": rid, "token": int(tok),
                                   "done": bool(done)})

            try:
                rid = self.engine.submit(
                    np.asarray(payload["prompt"], np.int32),
                    max_new_tokens=payload["max_new_tokens"],
                    eos_token=payload.get("eos"),
                    priority=payload.get("priority", Priority.NORMAL),
                    on_token=on_token)
            except Exception as e:
                # broad on purpose: this runs on the ENGINE thread, and
                # one malformed payload (e.g. prompt [null] -> numpy
                # TypeError) must cost that client a BadRequest, never
                # the thread every other client depends on
                pending.outbox.put({"error": "BadRequest",
                                    "reason": f"{type(e).__name__}: {e}"})
                pending.outbox.put(None)
                continue
            self._pending[rid] = pending

    def _on_complete(self, req) -> None:
        """Engine callback: terminal state for a request (any state)."""
        self.metrics.observe_request(req)
        # the reply below is the server's result delivery — drop the
        # engine's retained copy or a long-lived server accumulates
        # every DecodeRequest (and its outbox closure) ever finished
        self.engine.result(req.req_id, pop=True)
        pending = self._pending.pop(req.req_id, None)
        if pending is None:
            return  # engine used without the server front-end
        if req.state == "done":
            msg: Dict[str, Any] = {
                "rid": req.req_id, "done": True,
                "tokens": [int(t) for t in req.tokens],
                "generated": [int(t) for t in req.generated],
                "stats": _json_stats(req.stats)}
        elif req.state == "shed":
            cfg = getattr(self.scheduler, "cfg", None)
            msg = {"rid": req.req_id, "error": "ServerOverloaded",
                   "reason": "queued past SLO shed_after_s",
                   "retry_after_ms": getattr(cfg, "retry_after_ms", 1000)}
        elif req.state == "failed":
            msg = {"rid": req.req_id, "error": "PrefillFailed",
                   "attempts": req.stats.prefill_attempts}
        else:  # evicted (drain/close)
            msg = {"rid": req.req_id, "error": "ServerEvicted",
                   "reason": "server shutting down"}
        pending.outbox.put(msg)
        pending.outbox.put(None)

    # -- connection threads ------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                self._listen_sock.settimeout(0.2)
                conn, _addr = self._listen_sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True, name="pt-serving-conn")
            with self._conns_lock:
                self._conns.append(conn)
                # prune finished threads so a long-lived server doesn't
                # accumulate one Thread object per connection ever seen
                self._conn_threads = [x for x in self._conn_threads
                                      if x.is_alive()]
                self._conn_threads.append(t)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        rfile = conn.makefile("r", encoding="utf-8")
        wfile = conn.makefile("w", encoding="utf-8")

        def send(obj: Dict) -> None:
            wfile.write(json.dumps(obj) + "\n")
            wfile.flush()

        try:
            for line in rfile:
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError as e:
                    send({"error": "BadRequest", "reason": str(e)})
                    continue
                try:
                    self._handle(msg, send)
                except ServerOverloaded as e:
                    # submit-gate rejections get their own counter:
                    # engine-side sheds count under requests_total +
                    # shed_total, and mixing the two would let
                    # shed/requests ratios exceed 100%
                    self.metrics.counter("rejected_total").add()
                    send({"error": "ServerOverloaded",
                          "reason": e.reason,
                          "retry_after_ms": e.retry_after_ms})
                except Exception as e:  # typed reply, never a hang
                    send({"error": type(e).__name__, "reason": str(e)})
        except (OSError, ValueError):
            pass  # client went away / socket torn down by stop()
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._conns_lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def _handle(self, msg: Dict, send) -> None:
        from ..distributed.fault_inject import InjectedFault, fault_point

        op = msg.get("op", "generate")
        if op == "health":
            send(self._health())
            return
        if op == "stats":
            send({"stats": self.metrics.snapshot(),
                  "prefix_cache": self._cache_stats()})
            return
        if op == "metrics":
            send({"text": self.metrics.prometheus_text()})
            return
        if op == "drain":
            self.drain()
            send({"ok": True, "status": "draining"})
            return
        if op != "generate":
            send({"error": "BadRequest", "reason": f"unknown op {op!r}"})
            return
        if self._draining:
            send({"error": "ServerDraining",
                  "reason": "server is draining; not admitting"})
            return
        try:
            # per-request fault site: a transient front-end failure is
            # a retryable typed reply, not a dropped connection
            fault_point("serving.request")
        except InjectedFault as e:
            send({"error": "TransientServerError", "reason": str(e),
                  "retryable": True})
            return
        prompt = msg.get("prompt")
        if not isinstance(prompt, list) or not prompt:
            send({"error": "BadRequest",
                  "reason": "prompt must be a non-empty token list"})
            return
        mnt = int(msg.get("max_new_tokens", 16))
        if mnt < 1 or mnt > self.max_new_tokens_cap:
            send({"error": "BadRequest",
                  "reason": f"max_new_tokens must be in [1, "
                            f"{self.max_new_tokens_cap}]"})
            return
        prio = msg.get("priority", "normal")
        if prio not in _PRIORITIES:
            send({"error": "BadRequest",
                  "reason": f"priority must be one of "
                            f"{sorted(_PRIORITIES)}"})
            return
        pending = _Pending(stream=bool(msg.get("stream", False)))
        with self._admission_lock:
            # submit-time overload gate, atomic with the enqueue so
            # concurrent connections can't all slip under the depth
            # bound (raises ServerOverloaded -> typed reply upstream)
            check = getattr(self.scheduler, "check_admission", None)
            if check is not None:
                check(self.engine.num_queued + self._inbox.qsize())
            self._inbox.put(({"prompt": prompt, "max_new_tokens": mnt,
                              "eos": msg.get("eos"),
                              "priority": int(_PRIORITIES[prio])},
                             pending))
        self._wake.set()
        while True:
            try:
                out = pending.outbox.get(timeout=1.0)
            except queue_mod.Empty:
                if self._engine_done.is_set():
                    # closes the submit-vs-shutdown race: the engine
                    # thread has fully EXITED (mere stop() intent is
                    # not enough — graceful shutdown still finishes
                    # in-flight work and delivers real results), so
                    # this request can never complete; answer instead
                    # of hanging
                    send({"error": "ServerEvicted",
                          "reason": "server shutting down"})
                    return
                continue
            if out is None:
                return
            send(out)

    # -- introspection -----------------------------------------------------

    def _health(self) -> Dict:
        return {"status": "draining" if self._draining else "ok",
                "active": self.engine.num_active,
                "queued": self.engine.num_queued,
                "free_pages": self.engine.free_pages,
                "num_pages": self.engine.num_pages,
                "steps": self.engine.steps,
                "uptime_s": round(time.monotonic() - self._t0, 3)}

    def _cache_stats(self) -> Optional[Dict]:
        pc = self.prefix_cache
        if pc is None:
            return None
        return {"pages": pc.total_pages(), "hit_pages": pc.hit_pages,
                "miss_pages": pc.miss_pages,
                "inserted_pages": pc.inserted_pages,
                "evicted_pages": pc.evicted_pages,
                "hit_rate": pc.hit_rate()}


def _json_stats(stats) -> Dict:
    out = stats.to_dict()
    return {k: (round(v, 6) if isinstance(v, float) else v)
            for k, v in out.items() if v is not None}


def client_request(host: str, port: int, payload: Dict,
                   timeout_s: float = 120.0, on_token=None) -> Dict:
    """Minimal blocking client: send one request, collect streamed
    tokens through ``on_token(token)``, return the final reply."""
    with socket.create_connection((host, port), timeout=timeout_s) as s:
        rfile = s.makefile("r", encoding="utf-8")
        wfile = s.makefile("w", encoding="utf-8")
        wfile.write(json.dumps(payload) + "\n")
        wfile.flush()
        for line in rfile:
            msg = json.loads(line)
            if "token" in msg:  # streamed chunk (its "done" flag marks
                if on_token is not None:  # the LAST token, not the
                    on_token(msg["token"])  # final summary message)
                continue
            return msg  # final reply: summary, admin reply, or error
    raise ConnectionError("server closed the connection mid-request")


def _build_model(name: str):
    import paddle_tpu as pt
    from ..models.gpt import (GPTForCausalLM, gpt_125m, gpt_1p3b,
                              gpt_350m, gpt_tiny)
    configs = {"gpt_tiny": gpt_tiny, "gpt_125m": gpt_125m,
               "gpt_350m": gpt_350m, "gpt_1p3b": gpt_1p3b}
    if name not in configs:
        raise SystemExit(f"unknown --model {name!r}; choose from "
                         f"{sorted(configs)}")
    pt.seed(0)
    model = GPTForCausalLM(configs[name]())
    model.eval()
    return model


def main(argv=None) -> None:
    import argparse
    parser = argparse.ArgumentParser(
        description="paddle_tpu serving front-end (newline-JSON)")
    parser.add_argument("--model", default="gpt_125m")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8765)
    parser.add_argument("--num-slots", type=int, default=4)
    parser.add_argument("--page-size", type=int, default=64)
    parser.add_argument("--no-prefix-cache", action="store_true")
    parser.add_argument(
        "--speculate", type=int, default=0, metavar="K",
        help="draft K tokens per decode step and verify them in one "
             "forward (0 = off); greedy outputs stay bit-identical")
    parser.add_argument(
        "--draft-model", default="ngram",
        help="draft source for --speculate: 'ngram' (prompt lookup, "
             "no second model) or a model name (e.g. gpt_tiny)")
    parser.add_argument(
        "--draft-window", type=int, default=64,
        help="context window of a --draft-model draft")
    args = parser.parse_args(argv)

    model = _build_model(args.model)
    speculative = None
    if args.speculate > 0:
        from ..inference import SpeculativeConfig
        draft = args.draft_model
        if draft != "ngram":
            draft = _build_model(draft)
        speculative = SpeculativeConfig(k=args.speculate, draft=draft,
                                        draft_window=args.draft_window)
    server = ServingServer(model, host=args.host, port=args.port,
                           prefix_cache=not args.no_prefix_cache,
                           num_slots=args.num_slots,
                           page_size=args.page_size,
                           speculative=speculative)
    port = server.start()
    print(f"[paddle_tpu.serving] listening on {args.host}:{port} "
          f"(model {args.model}); newline-JSON, see module docstring",
          flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("[paddle_tpu.serving] draining ...", flush=True)
        server.stop()


if __name__ == "__main__":
    main()
