"""Threaded socket front-end over the continuous-batching engine.

Newline-JSON protocol (one JSON object per line, both directions):

    -> {"op": "generate", "prompt": [1, 2, 3], "max_new_tokens": 8,
        "priority": "interactive", "stream": true, "eos": 7,
        "deadline_ms": 5000, "key": "req-42"}
    <- {"rid": 0, "token": 17, "done": false}          # per token (stream)
    <- {"rid": 0, "done": true, "tokens": [...], "stats": {...}}
    -> {"op": "health"}
    <- {"status": "ok", "active": 1, "queued": 0, "free_pages": 9, ...}
    -> {"op": "stats"}     # metrics snapshot (JSON)
    -> {"op": "metrics"}   # Prometheus text page (in "text")
    -> {"op": "export"}    # structured metrics export (r17): exact
                           # counters + bucket-exact histogram counts
                           # + SLO window counts — what the
                           # supervisor's fleet collector scrapes
    -> {"op": "slo"}       # read / retarget the live SLO monitor
                           # ({"ttft_ms": 50, "tpot_ms": 10} sets and
                           # resets the rolling window)
    -> {"op": "trace"}     # finished span trees + engine step
                           # timeline (r16); {"format": "chrome"}
                           # returns chrome://tracing JSON mergeable
                           # with jax.profiler via tools/merge_traces
    -> {"op": "capacity"}  # memory observatory (r18): pool occupancy
                           # by owner class (inflight/prefix-device/
                           # reserved/free, summing to the pool), spill-
                           # tier residency, the page-ledger tail, and
                           # an EWMA time-to-exhaustion forecast over
                           # step-timeline ring deltas
    -> {"op": "profile"}   # on-demand device profiling: live per-
                           # device HBM accounting (device.memory_stats
                           # where the backend provides it; chip-pending
                           # gauges on CPU) and, with {"ms": N}, a
                           # jax.profiler capture window server-side —
                           # the engine keeps stepping, so the dump
                           # holds real serving steps (merge with span
                           # dumps via tools/merge_traces.py)
    -> {"op": "drain"}     # stop admitting, finish in-flight, close
    -> {"op": "leak_check"}  # engine-thread page-accounting audit
                             # (+ page-ledger reconciliation, r18)
    -> {"op": "fetch_pages"}  # disaggregated serving (r20): serve
                              # chain-page KV blobs (base64, crc32
                              # inside) to a peer replica by exact
                              # chain key and/or chain head (heads
                              # are expanded server-side); keys this
                              # replica cannot produce come back in
                              # "missing" — absence is never an error
    -> {"op": "prefetch"}  # pull a PEER's chains into this replica's
                           # spill tiers (the drain-handoff receiving
                           # side): {"host","port","heads":[hex...]}
                           # — fetch on the conn thread, crc-verified
                           # import on the engine thread

Disaggregated roles (r20): ``--role prefill`` serves prefill_only
requests (admission + chunked prefill; the finished chain parks in
its cache/tiers, the reply is a prefill-ack with the chain keys) and
rejects plain generates typed (WrongRole); ``--role decode`` accepts
a router-supplied ``"fetch_from": {"host", "port"}`` hint on generate
— the conn thread pulls the prompt's chain blobs from that peer
(fetch_pages), the engine imports them into the spill tiers, and
admission SPLICES them in instead of re-prefilling (greedy outputs
bit-identical handoff-vs-local; any fetch failure is a counted,
typed-internal PageFetchFailed fall-back to local prefill, never a
hang). ``--role mixed`` (default) is byte-for-byte the pre-r20
replica.

End-to-end tracing (r16): ``--trace-sample R`` samples a fraction R of
requests into per-request span trees (serving/tracing.py) covering
queue → admit → prefill chunks → decode/verify steps → complete,
stitched across engine resurrection and router failover; an incoming
``"trace": {"id": ..., "parent": ...}`` context (set by the failover
router) forces sampling so one trace id spans router and replica.
Dump via the ``trace`` op; validate with tools/trace_lint.py.

``deadline_ms`` is a completion budget measured from arrival: a
request that cannot finish in time is never admitted (shed from the
queue), and one already decoding is evicted mid-flight with its pages
(and any speculative reservation) returned — either way the client
gets a typed ``{"error": "DeadlineExceeded"}``, never a hang.
``key`` marks the request idempotent for the failover router
(serving/supervisor.py): greedy decoding is deterministic, so a keyed
request that dies with its replica is safely resubmitted to another.

Typed failures are structured replies, never hangs: an overloaded
queue answers ``{"error": "ServerOverloaded", "retry_after_ms": ...}``
(serving/scheduler.py), a prefill whose retries exhausted answers
``{"error": "PrefillFailed"}``, a drain answers in-flight requests
normally and rejects new ones with ``{"error": "ServerDraining"}``, a
slot that stops emitting answers ``{"error": "RequestStalled"}``
(``stall_timeout_s`` watchdog), an expired budget answers
``{"error": "DeadlineExceeded"}``.

Engine resurrection: when ``max_engine_errors`` consecutive step
failures mark the engine dead, the server does NOT fail its clients —
it tears the engine down (pages returned and audited), rebuilds it
(a PADDLE_TPU_COMPILE_CACHE dir makes the re-compiles cache reads),
and replays every in-flight request from its prompt + already-emitted
tokens as one chained greedy prefill. Greedy continuations are
bit-identical to the uninterrupted run, so clients just see a pause.
Only after ``max_engine_restarts`` resurrections does the server fail
typed (EngineFailed) and stop admitting.

Threading model: the ENGINE THREAD exclusively owns the engine (it is
not thread-safe) — connection threads parse requests and hand them
over through an inbox queue; per-token streaming flows back through
per-request outbox queues, so a slow client can never stall the decode
step. Graceful drain: stop admitting, finish in-flight work, return
every page, `engine.close()` (which asserts ``check_no_leak``).

Fault sites (distributed/fault_inject.py): ``serving.request`` fires
in the connection thread per request (clients get a retryable typed
error); ``serving.prefill`` fires inside engine admission and is
retried per the ``serving.prefill`` resilience policy; ``engine.step``
fires at the top of the decode step (persistent firing drives the
resurrection path); ``alloc.page`` fires in the page allocator
(admission requeues); ``net.recv`` tears the connection down like a
half-open socket (the failover router resubmits keyed requests).

Run it: ``python -m paddle_tpu.serving.server --model gpt_125m``.
Chunked prefill: ``--prefill-chunk 256`` admits long prompts without
stalling in-flight streams — each engine step prefills at most one
page-aligned 256-token chunk of one admitted prompt before the decode
step (the TTFT-vs-TPOT head-of-line fix; greedy outputs stay
bit-identical to whole prefill, and the ``serving_prefill_debt_tokens``
gauge tracks the outstanding work).
Multi-step decode: ``--multi-step 8`` runs 8 decode steps per device
program launch (r19: one on-device early-exit loop + a token ring
read back once per launch; the host schedules and streams while the
device computes). Greedy outputs stay bit-identical to the per-token
engine; admission and chunked-prefill boundaries coarsen to every N
steps, so keep N small for TTFT-sensitive traffic.
Speculative decoding: ``--speculate 4`` (n-gram/prompt-lookup draft,
no second model) or ``--speculate 4 --draft-model gpt_tiny`` (a small
model drafts; its greedy guesses are verified in one multi-token
forward, so greedy outputs stay bit-identical to the vanilla engine
while each accepted draft amortizes the weight/KV stream). Per-request
acceptance rate and tokens-per-step land in the ``stats`` reply and
the Prometheus ``metrics`` page.

Reference analog: the C serving API / AnalysisPredictor server loop
(SURVEY §1 rows 7/12), TPU-native over one jitted decode step.
"""

from __future__ import annotations

import json
import queue as queue_mod
import socket
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from .fleet_metrics import FlightRecorder
from .metrics import ServingMetrics, SLOAttainment
from .prefix_cache import PrefixCache
from .scheduler import Priority, ServerOverloaded, SLOScheduler
from .tracing import SpanTracer, stderr_span_sink

__all__ = ["ServingServer", "client_request", "PageFetchFailed",
           "fetch_page_blobs"]

import os as _os

# PT_SERVING_DEBUG=1: request-lifecycle tracing on stderr. Since r16
# this IS the span tracer at sample_rate=1.0 with the stderr span sink
# (serving/tracing.py) — one event vocabulary for live debugging, the
# ``trace`` op, and chrome-trace export, replacing the old ad-hoc
# print sites. The chaos harness's postmortems lean on it: it is how
# a request that vanishes between layers is localized.

_PRIORITIES = {"batch": Priority.BATCH, "normal": Priority.NORMAL,
               "interactive": Priority.INTERACTIVE}

_ROLES = ("mixed", "prefill", "decode")


class PageFetchFailed(ConnectionError):
    """A cross-replica page fetch (the r20 ``fetch_pages`` wire op)
    could not deliver usable blobs: peer dead, transport torn, typed
    peer error, or a malformed payload. ALWAYS recoverable — the
    caller falls back to local (chained) prefill, so the client sees
    identical greedy tokens, never a hang; the socket timeout bounds
    the wait and ``handoff_failures_total`` counts the fallback."""


def fetch_page_blobs(host: str, port: int, keys=None, heads=None,
                     timeout_s: float = 30.0):
    """Client side of the ``fetch_pages`` wire op: pull chain-page
    blobs from a peer replica. ``keys`` are exact chain keys (bytes or
    hex); ``heads`` are chain heads the PEER expands to their full
    chains (device subtree + spilled members — the drain-handoff
    path). Returns ``(blobs: {key_bytes: blob_bytes}, missing_hex,
    bytes_total)``; raises :class:`PageFetchFailed` on any transport
    or protocol failure. Blob integrity is NOT checked here — the
    importer re-verifies every crc32 before a blob can ever reach a
    splice (serving/prefix_cache.py ``import_blobs``).

    Chains longer than the peer's FETCH_PAGES_CAP page through the
    reply's ``next_cursor`` (r23): this client keeps pulling bounded
    windows until the peer stops returning one, so a long chain hands
    off WHOLE. Each window is its own timeout-bounded RPC."""
    import base64

    def hexes(ks):
        return [k.hex() if isinstance(k, bytes) else str(k)
                for k in ks]

    base: Dict[str, Any] = {"op": "fetch_pages"}
    if keys:
        base["keys"] = hexes(keys)
    if heads:
        base["heads"] = hexes(heads)
    blobs: Dict[bytes, bytes] = {}
    missing: List[str] = []
    total = 0
    cursor = 0
    # hard bound on pagination rounds: a buggy/malicious peer echoing
    # a never-advancing cursor must not spin this thread forever
    for _round in range(256):
        payload = dict(base)
        if cursor:
            payload["cursor"] = cursor
        try:
            reply = client_request(host, int(port), payload,
                                   timeout_s=timeout_s)
        except Exception as e:
            raise PageFetchFailed(f"{type(e).__name__}: {e}")
        if not isinstance(reply, dict) or reply.get("error"):
            raise PageFetchFailed(
                f"{reply.get('error')}: {reply.get('reason')}"
                if isinstance(reply, dict) else "non-object reply")
        try:
            for khex, b64 in (reply.get("blobs") or {}).items():
                blob = base64.b64decode(b64)
                blobs[bytes.fromhex(khex)] = blob
                total += len(blob)
        except Exception as e:
            raise PageFetchFailed(f"malformed blob payload: "
                                  f"{type(e).__name__}: {e}")
        missing.extend(reply.get("missing") or ())
        nxt = reply.get("next_cursor")
        if not isinstance(nxt, int) or nxt <= cursor:
            break
        cursor = nxt
    return blobs, missing, total


class _Pending:
    """Engine-side record of one in-flight client request."""

    __slots__ = ("outbox", "stream")

    def __init__(self, stream: bool):
        self.outbox: "queue_mod.Queue[Optional[Dict]]" = queue_mod.Queue()
        self.stream = stream


class ServingServer:
    """In-process serving front-end (tests construct it directly; the
    CLI entry below wraps it).

    ``engine_kwargs`` pass through to `create_decode_engine`
    (num_slots, page_size, num_pages, ...). ``prefix_cache=True``
    builds a `PrefixCache` sized to the engine's page_size;
    ``scheduler=None`` defaults to an `SLOScheduler` with stock
    SLOConfig. ``prefill_retry=None`` resolves the ``serving.prefill``
    site policy from distributed/resilience.py."""

    def __init__(self, model, host: str = "127.0.0.1", port: int = 0,
                 scheduler=None, prefix_cache: bool = True,
                 metrics: Optional[ServingMetrics] = None,
                 prefill_retry="site", max_new_tokens_cap: int = 512,
                 poll_interval_s: float = 0.02,
                 max_engine_errors: int = 32,
                 max_engine_restarts: int = 2,
                 spill_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 spill_disk_bytes: Optional[int] = None,
                 trace_sample: float = 0.0,
                 trace_max: int = 64,
                 tracer: Optional[SpanTracer] = None,
                 slo_ttft_ms: Optional[float] = None,
                 slo_tpot_ms: Optional[float] = None,
                 slo_window_s: float = 120.0,
                 flight_dir: Optional[str] = None,
                 flight_budget_bytes: int = 64 << 20,
                 role: str = "mixed",
                 handoff_timeout_s: float = 30.0,
                 blob_format: str = "raw",
                 dedup: bool = True,
                 checkpoint: Optional[str] = None,
                 weight_generation: int = 0,
                 **engine_kwargs):
        from ..distributed.resilience import get_retry_policy

        # disaggregated serving (r20): "mixed" (the default) is
        # byte-for-byte the pre-r20 replica. "prefill" runs admission
        # + (chunked) prefill only — plain generate ops get a typed
        # WrongRole; finished chains park in its cache/tiers and are
        # advertised for peers to fetch. "decode" serves streams and
        # pulls advertised chains over fetch_pages instead of
        # re-prefilling. Both non-mixed roles need a spill tier (the
        # parking lot / wire landing zone), so one is defaulted when
        # the caller configured none.
        if role not in _ROLES:
            raise ValueError(f"role must be one of {_ROLES}; got "
                             f"{role!r}")
        self.role = role
        self.handoff_timeout_s = float(handoff_timeout_s)
        if role != "mixed" and prefix_cache and spill_bytes is None \
                and spill_dir is None:
            spill_bytes = 64 << 20

        # end-to-end tracing (r16): one tracer shared by the server
        # and its (resurrected) engines so a request's span tree spans
        # the whole stack. PT_SERVING_DEBUG=1 forces sample_rate=1.0
        # with the stderr span sink — the unified debug mode.
        if tracer is not None:
            self.tracer = tracer
        else:
            rate, sink = float(trace_sample), None
            if _os.environ.get("PT_SERVING_DEBUG"):
                rate, sink = 1.0, stderr_span_sink
            self.tracer = SpanTracer(sample_rate=rate,
                                     max_traces=int(trace_max),
                                     on_span=sink)
        self.host = host
        self._requested_port = port
        self.scheduler = scheduler if scheduler is not None \
            else SLOScheduler()
        if metrics is not None:
            # the caller owns the SLOAttainment (window size included);
            # constructor kwargs overlay per-target, preserving any
            # target already configured there — the same partial-
            # retarget rule as the runtime "slo" op
            self.metrics = metrics
            if slo_ttft_ms is not None or slo_tpot_ms is not None:
                slo = self.metrics.slo
                slo.set_targets(
                    slo_ttft_ms if slo_ttft_ms is not None
                    else slo.ttft_ms,
                    slo_tpot_ms if slo_tpot_ms is not None
                    else slo.tpot_ms)
        else:
            # live SLO monitor (r17): targets from the CLI (or the
            # runtime "slo" op); without targets the tracker is inert
            # and exports no attainment gauges
            self.metrics = ServingMetrics(
                slo=SLOAttainment(ttft_ms=slo_ttft_ms,
                                  tpot_ms=slo_tpot_ms,
                                  window_s=slo_window_s))
        # crash flight recorder (r17): black-box bundles on engine
        # resurrection / terminal failure / stall — postmortems stop
        # depending on having had stderr attached
        self.flight = (FlightRecorder(flight_dir,
                                      budget_bytes=flight_budget_bytes)
                       if flight_dir else None)
        self._use_prefix_cache = bool(prefix_cache)
        # hierarchical prefix cache (r15): spill-tier config is part of
        # the resurrection recipe — a rebuilt engine gets the same
        # host-RAM/disk tiers (contents start empty; blobs reference
        # nothing outside themselves, but the old cache's books died
        # with the old allocator and clear() scrubbed its blobs)
        self._spill_bytes = spill_bytes
        self._spill_dir = spill_dir
        self._spill_disk_bytes = spill_disk_bytes
        # KV byte substrate (r23): the blob transport codec and the
        # cross-request dedup switch are resurrection-recipe state
        # like the tiers — a rebuilt cache packs/folds identically
        self._blob_format = str(blob_format)
        self._dedup = bool(dedup)
        self._page_size = int(engine_kwargs.get("page_size", 64))
        if prefill_retry == "site":
            prefill_retry = get_retry_policy("serving.prefill")
        # everything a rebuild needs, captured once: engine resurrection
        # constructs a bit-equivalent engine from these after a terminal
        # step failure (fresh allocator, fresh pools, fresh prefix
        # cache — the old one's books die with the old allocator)
        self._model = model
        self._prefill_retry = prefill_retry
        self._engine_kwargs = dict(engine_kwargs)
        pb = self._engine_kwargs.get("prompt_buckets")
        if pb:
            # resurrection replays prompt + already-emitted tokens as
            # ONE chained prefill, so every length up to max_seq_len
            # must be representable as a prompt — a custom bucket
            # ladder that stops short would turn a transparent replay
            # into ReplayFailed. Extend it; prefill jits retrace per
            # shape lazily, so the extra bucket costs nothing until a
            # replay (or a long prompt) first uses it.
            msl = int(self._engine_kwargs.get("max_seq_len")
                      or model.config.max_seq_len)
            self._engine_kwargs["prompt_buckets"] = sorted(
                set(int(x) for x in pb) | {msl})
        # weight hot-swap (r24): the CURRENT generation is part of the
        # resurrection recipe — a rebuilt engine and prefix cache come
        # back salted to the generation that was serving, and replicas
        # (re)spawned mid-roll join the fleet at the right generation
        # via --checkpoint/--weight-generation. A boot checkpoint is
        # applied to the model BEFORE the engine captures its
        # functional state; a missing/corrupt boot checkpoint fails
        # construction (the supervisor's ready probe owns recovery).
        self._weight_generation = int(weight_generation)
        self._checkpoint_dir = checkpoint
        if checkpoint:
            _step, state = self._load_checkpoint_state(checkpoint)
            missing = model.set_state_dict(state)
            if missing:
                raise ValueError(
                    f"boot checkpoint {checkpoint!r} is missing "
                    f"{len(missing)} weight leaves (e.g. "
                    f"{missing[0]!r})")
        self.prefix_cache: Optional[PrefixCache] = None
        self.engine = self._build_engine()
        self.max_new_tokens_cap = int(max_new_tokens_cap)
        self.poll_interval_s = float(poll_interval_s)
        self.max_engine_errors = int(max_engine_errors)
        self.max_engine_restarts = int(max_engine_restarts)
        self._consec_errors = 0
        self._restarts = 0
        # replay ledger: new req_id -> (original prompt, tokens already
        # delivered before the crash, the original request's stats);
        # _on_complete stitches the full sequence — and the telemetry —
        # back together for the final reply
        self._replay: Dict[int, tuple] = {}
        self.metrics.set_gauge_fn(self._gauges)

        # pending weight swap (engine thread): (ctl payload, _Pending,
        # drain deadline). While set, engine admission is paused so
        # active slots can drain to zero — queued and newly-arriving
        # generates WAIT in the engine queue (zero drops, a TTFT dip)
        self._swap_pending: Optional[tuple] = None
        self._inbox: "queue_mod.Queue[tuple]" = queue_mod.Queue()
        self._admission_lock = threading.Lock()
        self._pending: Dict[int, _Pending] = {}  # engine thread only
        self._wake = threading.Event()
        self._engine_done = threading.Event()
        self._draining = False
        self._stopping = False
        self._started = False
        self._listen_sock: Optional[socket.socket] = None
        self._threads = []
        self._conn_threads = []
        self._conns = []
        self._conns_lock = threading.Lock()
        self._t0 = time.monotonic()
        # step-histogram scrape marker: (engine identity, last step
        # observed) — resurrection swaps the engine and resets it
        self._tl_seen: tuple = (None, -1)
        # macro-launch scrape marker (r19): (restart epoch, engine
        # launches already counted) — the serving_macro_steps_total
        # counter accumulates deltas so a resurrection's reset engine
        # counter never winds it backwards
        self._macro_seen: tuple = (None, 0)
        # one jax.profiler capture at a time (r18 profile op)
        self._profile_lock = threading.Lock()
        self.port: Optional[int] = None

    def _build_engine(self):
        """(Re)build the decode engine from the captured construction
        recipe. The prefix cache is rebuilt too: its books reference
        pages in the engine's allocator, so a cache may never outlive
        its engine. A PADDLE_TPU_COMPILE_CACHE dir (core/compile_cache,
        enabled inside the engine constructor) turns the rebuilt
        engine's prefill/decode/verify compiles into cache reads — the
        warm-resurrection lane."""
        from ..inference import create_decode_engine
        self.prefix_cache = (
            PrefixCache(self._page_size,
                        spill_bytes=self._spill_bytes,
                        spill_dir=self._spill_dir,
                        disk_bytes=self._spill_disk_bytes,
                        blob_format=self._blob_format,
                        dedup=self._dedup,
                        generation=self._weight_generation)
            if self._use_prefix_cache else None)
        return create_decode_engine(
            self._model, scheduler=self.scheduler,
            prefix_cache=self.prefix_cache,
            prefill_retry=self._prefill_retry,
            weight_generation=self._weight_generation,
            on_complete=self._on_complete,
            # the SAME tracer across resurrections: a replayed
            # request's spans land on its original tree. Program-cost
            # capture is on for served engines — the scrape gauges
            # (serving_program_*) are this server's to export.
            tracer=self.tracer, capture_costs=True,
            **self._engine_kwargs)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> int:
        """Bind, listen, and start the accept + engine threads.
        Returns the bound port (OS-assigned when constructed with
        port=0)."""
        if self._started:
            return self.port
        self._listen_sock = socket.socket(socket.AF_INET,
                                          socket.SOCK_STREAM)
        self._listen_sock.setsockopt(socket.SOL_SOCKET,
                                     socket.SO_REUSEADDR, 1)
        self._listen_sock.bind((self.host, self._requested_port))
        self._listen_sock.listen(64)
        self.port = self._listen_sock.getsockname()[1]
        self._started = True
        for name, fn in (("engine", self._engine_loop),
                         ("accept", self._accept_loop)):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"pt-serving-{name}")
            t.start()
            self._threads.append(t)
        return self.port

    def drain(self) -> None:
        """Stop admitting new requests; in-flight and already-queued
        work finishes normally."""
        self._draining = True
        self._wake.set()

    def stop(self, timeout_s: float = 60.0) -> None:
        """Graceful shutdown: drain, finish in-flight, return pages
        (engine.close() asserts check_no_leak), close sockets."""
        self._draining = True
        self._stopping = True
        self._wake.set()
        for t in self._threads:
            if t is threading.current_thread():
                continue
            t.join(timeout=timeout_s)
        if self._listen_sock is not None:
            try:
                self._listen_sock.close()
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns)
            threads = list(self._conn_threads)
        # let conn threads FLUSH first: the engine thread has exited,
        # so every pending outbox resolves (result or ServerEvicted)
        # within one poll tick — tearing the sockets down before that
        # relay races the final reply and a graceful client sees EOF
        # mid-request instead of its typed answer. Clients that close
        # after the reply release their conn thread immediately; idle
        # keep-alive readers hold readline open, so the wait is
        # bounded and stragglers are force-closed below.
        flush_deadline = time.monotonic() + 5.0
        for t in threads:
            if t is threading.current_thread():
                continue
            t.join(timeout=max(0.0,
                               flush_deadline - time.monotonic()))
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for t in threads:
            if t is threading.current_thread():
                continue
            t.join(timeout=5.0)

    def __enter__(self) -> "ServingServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- engine thread -----------------------------------------------------

    def _engine_loop(self) -> None:
        """Engine-thread entry: the no-hang contract is STRUCTURAL —
        whatever escapes the serving loop below (it should handle
        everything itself) becomes a typed EngineFailed broadcast plus
        ``_engine_done``, never a silently dead thread with clients
        spinning on their outboxes forever."""
        try:
            self._engine_loop_inner()
        except Exception:
            try:
                self._fail_engine()
            finally:
                self._engine_done.set()

    def _engine_loop_inner(self) -> None:
        while True:
            # re-read self.engine every iteration: resurrection swaps
            # the instance mid-loop
            eng = self.engine
            self._drain_inbox()
            self._maybe_apply_swap(eng)
            has_work = eng.num_queued or eng.num_active
            if has_work:
                try:
                    before = eng.num_queued + eng.num_active
                    eng.step()
                    after = eng.num_queued + eng.num_active
                    self._consec_errors = 0
                    if after and after == before and not eng.num_active:
                        # queued but nothing admissible and nothing
                        # decoding: don't hot-spin on the free list
                        time.sleep(self.poll_interval_s)
                except Exception:
                    # a failed prefill already unwound inside the
                    # engine (request requeued, or FAILED with a typed
                    # reply via on_complete) — the serving loop must
                    # outlive it either way. A PERSISTENT step failure
                    # (decode jit broken, pools consumed) must not
                    # wedge clients forever: past the consecutive-error
                    # cap the engine is RESURRECTED — torn down, pages
                    # audited, rebuilt, and every in-flight request
                    # replayed from its token history (clients see a
                    # pause, not an error); only when restarts are
                    # exhausted too does the server fail typed and
                    # stop admitting.
                    self.metrics.counter("engine_errors_total").add()
                    self._consec_errors += 1
                    # a failing step never reaches its own deadline /
                    # stall sweeps — run them here so a broken engine
                    # still sheds doomed work typed instead of letting
                    # requests ride the outage into a hang
                    try:
                        self.engine.expire_deadlines()
                        self.engine.evict_stalled()
                    except Exception:
                        pass
                    if self._consec_errors >= self.max_engine_errors:
                        if self._restarts < self.max_engine_restarts:
                            try:
                                self._resurrect_engine()
                            except Exception:
                                # the rebuild/replay failed too —
                                # almost certainly the same root cause
                                # that broke the engine. Terminal and
                                # TYPED, never a dead thread.
                                self.metrics.counter(
                                    "engine_resurrect_failures_total"
                                ).add()
                                self._fail_engine()
                        else:
                            self._fail_engine()
                    time.sleep(self.poll_interval_s)
                continue
            if self._stopping and self._inbox.empty():
                self._resolve_swap_pending(
                    {"error": "ServerEvicted",
                     "reason": "server shutting down"})
                try:
                    eng.close()
                finally:
                    # unblock any conn thread still waiting on a
                    # pending outbox (evicted replies already sent by
                    # close() -> on_complete)
                    for p in self._pending.values():
                        p.outbox.put(None)
                    self._pending.clear()
                    self._engine_done.set()
                return
            self._wake.wait(timeout=self.poll_interval_s)
            self._wake.clear()

    def _flight_record(self, reason: str, inflight=None,
                       **extra) -> None:
        """Crash flight recorder (r17): assemble + atomically write
        one black-box bundle (engine thread; bounded structures only —
        timeline ring, finished-trace ring, slot-count inflight dump).
        Never raises: a postmortem artifact must not create the next
        incident."""
        if self.flight is None:
            return
        eng = self.engine

        def collect() -> Dict:
            reqs = (inflight if inflight is not None
                    else eng.dump_inflight())
            return {
                # v2 bundles (r18) carry the page-ledger tail and a
                # capacity snapshot; tools/flight_inspect.py requires
                # and lints both at this version
                "v": 2,
                "page_ledger": getattr(eng, "ledger_tail",
                                       lambda n: [])(256),
                "capacity": self._capacity(),
                "model": type(self._model).__name__,
                # weight hot-swap (r24): which generation was serving
                # when the bundle was cut (flight_inspect lints it)
                "weight_generation": self._weight_generation,
                "engine": getattr(eng, "flight_summary",
                                  lambda: {})(),
                "recipe": dict(self._engine_kwargs),
                "restarts": self._restarts,
                "consec_errors": self._consec_errors,
                "step_timeline": getattr(eng, "step_timeline",
                                         lambda: [])(),
                "traces": self.tracer.finished(),
                "events": self.tracer.events(),
                "metrics": self.metrics.export(),
                "inflight": [{"req_id": int(r.req_id),
                              "state": r.state,
                              "prompt_len": int(len(r.prompt)),
                              "generated": int(len(r.generated)),
                              "priority": int(r.priority)}
                             for r in reqs],
                **extra,
            }

        self.flight.record(reason, collect)

    def _resurrect_engine(self) -> None:
        """Terminal engine-step failure, recoverable edition (engine
        thread): snapshot every request the dead engine still owes an
        answer for, tear the engine down (pages returned and audited by
        ``close()``), rebuild it from the captured recipe, and REPLAY
        each in-flight request — its prompt plus already-emitted tokens
        resubmitted as one chained greedy prefill, so the continuation
        is bit-identical to the uninterrupted run and the client sees a
        pause instead of an error. Requests still in the server inbox
        are untouched: the next ``_drain_inbox`` submits them to the
        new engine."""
        self._restarts += 1
        self.metrics.counter("engine_restarts_total").add()
        old = self.engine
        snapshot = old.dump_inflight()
        # flight bundle BEFORE teardown: the dying engine's timeline
        # ring and in-flight set are exactly what the postmortem needs
        self._flight_record("resurrect", inflight=snapshot)
        self.tracer.annotate(
            "resurrect",
            rids=[(r.req_id, len(r.prompt), len(r.generated), r.state)
                  for r in snapshot],
            pending=sorted(self._pending), inbox=self._inbox.qsize(),
            restarts=self._restarts)
        # detach each in-flight request's TRACE before teardown: the
        # close() below evicts every slot, and the engine's terminal
        # path would otherwise FINISH the tree — the replayed request
        # must keep appending to it (one tree across the stitch, the
        # r16 contract). The open stage span is closed typed here.
        saved_traces: Dict[int, Any] = {}
        for req in snapshot:
            tr = req.trace
            if tr is None:
                continue
            if req.span is not None:
                tr.end(req.span, state="resurrect")
                req.span = None
            tr.event("resurrect_replay", parent=tr.anchor,
                     restarts=self._restarts,
                     pre_tokens=len(req.generated))
            saved_traces[req.req_id] = tr
            req.trace = None
        # detach the completion hook BEFORE close(): teardown evictions
        # are an implementation detail of the restart, not terminal
        # replies the clients should see
        old.set_on_complete(None)
        try:
            old.close()
        except Exception:
            # a torn allocator is possible when the failure hit
            # half-applied host state; the old engine (and its pools)
            # are dropped wholesale either way — count it, don't die
            self.metrics.counter("engine_teardown_leaks_total").add()
        self.engine = self._build_engine()
        if self._swap_pending is not None:
            # a swap was draining when the engine died: the rebuilt
            # engine must keep the admission gate down or the replays
            # below pin slots forever against the pending swap
            self.engine.pause_admission = True
        for req in snapshot:
            pending = self._pending.pop(req.req_id, None)
            # compose across repeated resurrections: the snapshot's
            # prompt may itself be a replay prompt
            prior = self._replay.pop(req.req_id, None)
            if prior is not None:
                orig_prompt, pre, orig_stats = prior
                pre = list(pre) + [int(t) for t in req.generated]
            else:
                orig_prompt = [int(t) for t in req.prompt]
                pre = [int(t) for t in req.generated]
                orig_stats = req.stats
            replay_prompt = np.concatenate(
                [req.prompt, np.asarray(req.generated, np.int32)])
            remaining = req.max_new_tokens - len(req.generated)
            on_token = None
            if pending is not None and pending.stream:
                def on_token(rid, tok, done, _p=pending):
                    _p.outbox.put({"rid": rid, "token": int(tok),
                                   "done": bool(done)})
            try:
                new_rid = self.engine.submit(
                    replay_prompt, max_new_tokens=remaining,
                    eos_token=req.eos_token, priority=req.priority,
                    deadline_t=req.deadline_t, on_token=on_token,
                    # a handoff-blocking prefill job keeps its boost
                    # across resurrection — a decode replica is still
                    # waiting on the chain (r20)
                    handoff=getattr(req, "handoff", False),
                    # continue the original span tree on the rebuilt
                    # engine — queue/admit/prefill/decode spans of the
                    # replay append after the resurrect_replay marker
                    trace=saved_traces.get(req.req_id))
            except Exception as e:
                self.tracer.annotate(
                    "replay_failed", old_rid=req.req_id,
                    error=f"{type(e).__name__}: {e}")
                tr = saved_traces.get(req.req_id)
                if tr is not None:
                    tr.event("complete", parent=tr.anchor,
                             state="replay_failed")
                    self.tracer.finish(tr, state="replay_failed")
                if pending is not None:
                    pending.outbox.put(
                        {"error": "ReplayFailed",
                         "reason": f"{type(e).__name__}: {e}"})
                    pending.outbox.put(None)
                continue
            self.metrics.counter("replayed_requests_total").add()
            self.tracer.annotate(
                "replay", old_rid=req.req_id, new_rid=new_rid,
                pending=pending is not None)
            self._replay[new_rid] = (orig_prompt, pre, orig_stats)
            if pending is not None:
                self._pending[new_rid] = pending
        self._consec_errors = 0
        self._wake.set()

    def _fail_engine(self) -> None:
        """Terminal engine failure (engine thread): every in-flight and
        inboxed client gets a typed EngineFailed reply, the engine's
        pages are torn down best-effort, and the server stops admitting
        (health keeps answering with status "draining")."""
        self._draining = True
        self._flight_record("engine_failed")
        self._resolve_swap_pending(
            {"error": "SwapFailed",
             "reason": "engine failed terminally before the swap "
                       "could apply"})
        err = {"error": "EngineFailed",
               "reason": f"decode engine failed "
                         f"{self._consec_errors} consecutive steps; "
                         f"server stopped admitting"}
        try:
            self.engine.close()  # sends ServerEvicted via on_complete
        except Exception:
            pass
        for p in self._pending.values():
            p.outbox.put(dict(err))
            p.outbox.put(None)
        self._pending.clear()
        while True:
            try:
                _payload, p = self._inbox.get_nowait()
            except queue_mod.Empty:
                break
            p.outbox.put(dict(err))
            p.outbox.put(None)

    def _drain_inbox(self) -> None:
        while True:
            try:
                payload, pending = self._inbox.get_nowait()
            except queue_mod.Empty:
                return
            if payload.get("ctl") == "leak_check":
                # page-accounting audit, answered ON the engine thread
                # so it never races a step's allocator mutations (the
                # chaos harness's per-replica invariant probe)
                pending.outbox.put(self._leak_check())
                pending.outbox.put(None)
                continue
            if payload.get("ctl") == "fetch_pages":
                # r20 handoff serving side: pack/serve chain blobs ON
                # the engine thread — device reads (pack_page_blob via
                # _read_page) and tier index walks must not race a
                # step's pool donation or LRU mutation
                pending.outbox.put(self._serve_fetch_pages(payload))
                pending.outbox.put(None)
                continue
            if payload.get("ctl") == "import_blobs":
                # r20 handoff/prefetch receiving side: tier puts are
                # engine-thread state (the conn thread already did the
                # network pull; this is dict inserts + crc checks)
                pending.outbox.put(self._import_blobs(payload))
                pending.outbox.put(None)
                continue
            if payload.get("ctl") == "swap":
                # weight hot-swap (r24): the conn thread already
                # loaded + crc-validated the checkpoint; park the
                # apply until active slots drain (admission pauses,
                # nothing is dropped — _maybe_apply_swap finishes it)
                if self._swap_pending is not None:
                    pending.outbox.put(
                        {"error": "SwapFailed",
                         "reason": "another weight swap is already "
                                   "pending on this replica"})
                    pending.outbox.put(None)
                    continue
                self.engine.pause_admission = True
                deadline = time.monotonic() + float(
                    payload.get("timeout_s") or 120.0)
                self._swap_pending = (payload, pending, deadline)
                continue

            def on_token(rid, tok, done, _p=pending):
                if _p.stream:
                    _p.outbox.put({"rid": rid, "token": int(tok),
                                   "done": bool(done)})

            # r20: a generate that rode a wire handoff carries the
            # fetched blobs — import them into the cache tiers NOW so
            # this request's admission restores+splices them instead
            # of re-prefilling (corrupt blobs are dropped counted by
            # the crc re-verify; missing ones fall to chained prefill)
            handoff_info = None
            ho = payload.pop("_handoff", None)
            if ho is not None:
                pc = self.prefix_cache
                if pc is not None and getattr(pc, "tiers", None):
                    rep = pc.import_blobs(ho["blobs"],
                                          heads=ho.get("heads", ()))
                    handoff_info = {"ms": ho["ms"], "bytes": ho["bytes"],
                                    "imported": rep["imported"],
                                    "corrupt": rep["corrupt"]}
                    if rep["corrupt"] and not rep["imported"]:
                        # every fetched blob failed its crc re-verify:
                        # the handoff delivered nothing — a counted
                        # fallback to local prefill
                        self.metrics.counter(
                            "handoff_failures_total").add()
            try:
                rid = self.engine.submit(
                    np.asarray(payload["prompt"], np.int32),
                    max_new_tokens=payload["max_new_tokens"],
                    eos_token=payload.get("eos"),
                    priority=payload.get("priority", Priority.NORMAL),
                    deadline_t=payload.get("deadline_t"),
                    on_token=on_token,
                    # a prefill_only job is handoff-blocking: the
                    # router is mid-handoff and a decode replica waits
                    # on this chain (scheduler boost, r20)
                    handoff=bool(payload.get("handoff")),
                    handoff_info=handoff_info,
                    # upstream trace context (the failover router's
                    # forward span) forces sampling and links this
                    # replica's tree under the router's; without it
                    # the engine's own sampler decides
                    trace_ctx=payload.get("trace_ctx"))
            except Exception as e:
                # broad on purpose: this runs on the ENGINE thread, and
                # one malformed payload (e.g. prompt [null] -> numpy
                # TypeError) must cost that client a BadRequest, never
                # the thread every other client depends on
                pending.outbox.put({"error": "BadRequest",
                                    "reason": f"{type(e).__name__}: {e}"})
                pending.outbox.put(None)
                continue
            self._pending[rid] = pending

    # -- weight hot-swap (r24) ----------------------------------------------

    def _resolve_swap_pending(self, reply: Dict) -> None:
        """Answer (and clear) a parked swap with ``reply`` — the
        shutdown / terminal-failure escape so the swapping client can
        never hang on its outbox (engine thread)."""
        if self._swap_pending is None:
            return
        _payload, pending, _deadline = self._swap_pending
        self._swap_pending = None
        self.metrics.counter("weight_swaps_failed_total").add()
        pending.outbox.put(dict(reply))
        pending.outbox.put(None)

    def _maybe_apply_swap(self, eng) -> None:
        """Engine-thread gate of a parked swap: once active slots
        drain to zero (admission is paused, so they only ever shrink),
        apply it between steps; past the drain deadline, fail it typed
        with the old weights still serving."""
        if self._swap_pending is None:
            return
        payload, pending, deadline = self._swap_pending
        if eng.num_active and time.monotonic() < deadline:
            return  # active slots still finishing on the old weights
        self._swap_pending = None
        reply = self._apply_swap(eng, payload)
        eng.pause_admission = False
        self._wake.set()
        pending.outbox.put(reply)
        pending.outbox.put(None)

    def _apply_swap(self, eng, payload: Dict) -> Dict:
        """Apply a drained, pre-validated swap (engine thread). Any
        failure is a typed SwapFailed reply — the engine refused
        before touching live state, so the old generation keeps
        serving, pinned."""
        from ..inference.continuous_batching import SwapFailed
        outcome = ("rolled_back" if payload.get("rollback")
                   else "committed")
        if eng.num_active:
            self.metrics.counter("weight_swaps_failed_total").add()
            return {"error": "SwapFailed",
                    "reason": f"engine did not drain its "
                              f"{eng.num_active} active slot(s) "
                              f"within the swap timeout"}
        try:
            info = eng.swap_weights(payload["state"],
                                    generation=payload.get("generation"))
        except SwapFailed as e:
            self.metrics.counter("weight_swaps_failed_total").add()
            self._flight_record("swap_failed", swap_error=str(e))
            return {"error": "SwapFailed", "reason": str(e)}
        except Exception as e:
            self.metrics.counter("weight_swaps_failed_total").add()
            self._flight_record(
                "swap_failed",
                swap_error=f"{type(e).__name__}: {e}")
            return {"error": "SwapFailed",
                    "reason": f"{type(e).__name__}: {e}"}
        self._weight_generation = int(info["generation"])
        self.metrics.counter(f"weight_swaps_{outcome}_total").add()
        self.metrics.swap_ms.observe(float(info["swap_ms"]))
        self.tracer.annotate("weight_swap", outcome=outcome,
                             generation=info["generation"],
                             swap_ms=info["swap_ms"],
                             checkpoint_step=payload.get("step"))
        return {"ok": True, "outcome": outcome, **info}

    @staticmethod
    def _load_checkpoint_state(directory: str):
        """Load + crc-validate the newest valid checkpoint under
        ``directory`` (ResilientCheckpointManager manifest layout) on
        the CALLING thread — the live engine is never touched. The
        ``checkpoint.load`` fault site fires per attempt and transient
        faults retry per its builtin policy; a directory with no valid
        checkpoint raises a typed SwapFailed. Returns (step, state)."""
        from ..distributed.fault_inject import fault_point
        from ..distributed.resilience import (
            ResilientCheckpointManager, get_retry_policy)
        from ..inference.continuous_batching import SwapFailed

        def load_once():
            fault_point("checkpoint.load")
            mgr = ResilientCheckpointManager(directory)
            got = mgr.restore_latest_valid()
            if got is None:
                raise SwapFailed(
                    f"no valid checkpoint under {directory!r} "
                    f"(skipped corrupt/partial steps: "
                    f"{mgr.last_skipped})")
            return got

        policy = get_retry_policy("checkpoint.load")
        return policy.call(load_once, site="checkpoint.load")

    def _swap(self, msg: Dict, send) -> None:
        """The ``swap`` op (conn thread): load-and-validate the new
        checkpoint fully BEFORE the engine hears about it — a torn or
        corrupt checkpoint is a typed SwapFailed with the old weights
        still serving — then hand the host-side state to the engine
        thread, which drains active slots and applies it between
        steps. Queued and newly-arriving generates wait (zero drops);
        the reply carries the new generation and swap_ms."""
        from ..distributed.resilience import RetryExhausted
        from ..inference.continuous_batching import SwapFailed
        ckpt = msg.get("checkpoint")
        if not isinstance(ckpt, str) or not ckpt:
            send({"error": "BadRequest",
                  "reason": "swap needs 'checkpoint': a checkpoint-"
                            "manager directory path"})
            return
        gen = msg.get("generation")
        if gen is not None and (isinstance(gen, bool)
                                or not isinstance(gen, int)
                                or gen < 0):
            send({"error": "BadRequest",
                  "reason": "generation must be a non-negative int"})
            return
        timeout_s = msg.get("timeout_s")
        if timeout_s is not None and (
                isinstance(timeout_s, bool)
                or not isinstance(timeout_s, (int, float))
                or timeout_s <= 0):
            send({"error": "BadRequest",
                  "reason": "timeout_s must be a positive number of "
                            "seconds"})
            return
        try:
            step, state = self._load_checkpoint_state(ckpt)
        except (SwapFailed, RetryExhausted) as e:
            self.metrics.counter("weight_swaps_failed_total").add()
            send({"error": "SwapFailed", "reason": str(e)})
            return
        except Exception as e:
            self.metrics.counter("weight_swaps_failed_total").add()
            send({"error": "SwapFailed",
                  "reason": f"{type(e).__name__}: {e}"})
            return
        payload: Dict[str, Any] = {"ctl": "swap", "state": state,
                                   "step": step}
        if gen is not None:
            payload["generation"] = gen
        if msg.get("rollback"):
            payload["rollback"] = True
        if timeout_s is not None:
            payload["timeout_s"] = float(timeout_s)
        pending = _Pending(stream=False)
        self._inbox.put((payload, pending))
        self._wake.set()
        self._await_outbox(pending, send)

    def _on_complete(self, req) -> None:
        """Engine callback: terminal state for a request (any state)."""
        replay = self._replay.pop(req.req_id, None)
        if replay is not None:
            # telemetry must describe the request the CLIENT
            # experienced — one generation from the original submit,
            # every pre-crash token included — not the
            # post-resurrection slice (which would undercount
            # tokens_generated_total and report replay-relative
            # latencies)
            orig_prompt, pre, orig_stats = replay
            st = req.stats
            st.tokens_out = len(req.generated) + len(pre)
            st.prompt_len = len(orig_prompt)
            st.submit_t = orig_stats.submit_t
            if orig_stats.admit_t:
                st.admit_t = orig_stats.admit_t
            if orig_stats.first_token_t:
                st.first_token_t = orig_stats.first_token_t
            if orig_stats.prefill_ms:
                st.prefill_ms = orig_stats.prefill_ms
        self.metrics.observe_request(req)
        # the reply below is the server's result delivery — drop the
        # engine's retained copy or a long-lived server accumulates
        # every DecodeRequest (and its outbox closure) ever finished
        self.engine.result(req.req_id, pop=True)
        pending = self._pending.pop(req.req_id, None)
        if pending is None:
            return  # engine used without the server front-end
        if req.state == "done":
            tokens = [int(t) for t in req.tokens]
            generated = [int(t) for t in req.generated]
            stats = _json_stats(req.stats)
            if replay is not None:
                # a resurrected engine served the tail of this request;
                # the reply must read as ONE uninterrupted generation:
                # original prompt, pre-crash tokens stitched back in
                # front of the replayed continuation
                orig_prompt, pre, _orig_stats = replay
                generated = list(pre) + generated
                tokens = list(orig_prompt) + generated
                stats["tokens_out"] = len(generated)
                stats["replayed"] = True
            msg: Dict[str, Any] = {
                "rid": req.req_id, "done": True,
                "tokens": tokens, "generated": generated,
                "stats": stats}
        elif req.state == "deadline":
            msg = {"rid": req.req_id, "error": "DeadlineExceeded",
                   "reason": "deadline_ms elapsed before completion",
                   "tokens_out": int(req.stats.tokens_out)}
            fors = getattr(req, "page_forensics", None)
            if fors:
                # memory observatory (r18): the unwound request's page
                # ownership history rides the typed reply (bounded)
                msg["page_forensics"] = fors[-8:]
        elif req.state == "stalled":
            # a stall is the third black-box trigger: something below
            # the engine stopped making progress without erroring —
            # the rate-limited bundle captures the step timeline that
            # explains the silence (r17)
            fors = getattr(req, "page_forensics", None)
            self._flight_record("stall", stalled_rid=int(req.req_id),
                                page_forensics=fors or [])
            msg = {"rid": req.req_id, "error": "RequestStalled",
                   "reason": f"no token for "
                             f"{self.engine.stall_timeout_s}s; evicted",
                   "tokens_out": int(req.stats.tokens_out)}
            if fors:
                msg["page_forensics"] = fors[-8:]
        elif req.state == "shed":
            cfg = getattr(self.scheduler, "cfg", None)
            msg = {"rid": req.req_id, "error": "ServerOverloaded",
                   "reason": "queued past SLO shed_after_s",
                   "retry_after_ms": getattr(cfg, "retry_after_ms", 1000)}
        elif req.state == "failed":
            msg = {"rid": req.req_id, "error": "PrefillFailed",
                   "attempts": req.stats.prefill_attempts}
        else:  # evicted (drain/close)
            msg = {"rid": req.req_id, "error": "ServerEvicted",
                   "reason": "server shutting down"}
        pending.outbox.put(msg)
        pending.outbox.put(None)

    # -- connection threads ------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                self._listen_sock.settimeout(0.2)
                conn, _addr = self._listen_sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True, name="pt-serving-conn")
            with self._conns_lock:
                self._conns.append(conn)
                # prune finished threads so a long-lived server doesn't
                # accumulate one Thread object per connection ever seen
                self._conn_threads = [x for x in self._conn_threads
                                      if x.is_alive()]
                self._conn_threads.append(t)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        rfile = conn.makefile("r", encoding="utf-8")
        wfile = conn.makefile("w", encoding="utf-8")

        def send(obj: Dict) -> None:
            wfile.write(json.dumps(obj) + "\n")
            wfile.flush()

        from ..distributed.fault_inject import InjectedFault, fault_point

        try:
            for line in rfile:
                try:
                    # chaos site: a torn receive. The connection dies
                    # exactly like a real half-open TCP teardown — the
                    # failover router (serving/supervisor.py) resubmits
                    # keyed requests to a live replica; unkeyed clients
                    # see a clean close, never a hang.
                    fault_point("net.recv")
                except InjectedFault:
                    self.metrics.counter("net_recv_drops_total").add()
                    # the peer must see the teardown NOW: shutdown()
                    # sends the FIN even while rfile/wfile still hold
                    # references to the socket (close() alone defers
                    # to their refcounts — a GC-timing hang, not a
                    # torn connection)
                    try:
                        conn.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    break
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError as e:
                    send({"error": "BadRequest", "reason": str(e)})
                    continue
                try:
                    self._handle(msg, send)
                except ServerOverloaded as e:
                    # submit-gate rejections get their own counter:
                    # engine-side sheds count under requests_total +
                    # shed_total, and mixing the two would let
                    # shed/requests ratios exceed 100%
                    self.metrics.counter("rejected_total").add()
                    send({"error": "ServerOverloaded",
                          "reason": e.reason,
                          "retry_after_ms": e.retry_after_ms})
                except Exception as e:  # typed reply, never a hang
                    send({"error": type(e).__name__, "reason": str(e)})
        except (OSError, ValueError):
            pass  # client went away / socket torn down by stop()
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._conns_lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def _handle(self, msg: Dict, send) -> None:
        from ..distributed.fault_inject import InjectedFault, fault_point

        op = msg.get("op", "generate")
        if op == "health":
            send(self._health())
            return
        if op == "stats":
            eng = self.engine
            send({"stats": self.metrics.snapshot(),
                  "prefix_cache": self._cache_stats(),
                  # step timeline tail (r16) — the full ring rides the
                  # "trace" op; program launch totals by kind
                  "step_timeline": getattr(
                      eng, "step_timeline", lambda: [])()[-16:],
                  "programs_launched": dict(
                      getattr(eng, "programs_launched", {}) or {}),
                  # multi-step decode (r19)
                  "multi_step": getattr(eng, "multi_step", 1),
                  "macro_launches": getattr(eng, "macro_launches", 0),
                  # weight hot-swap (r24)
                  "weight_generation": self._weight_generation,
                  "weight_swaps": getattr(eng, "weight_swaps", 0)})
            return
        if op == "metrics":
            send({"text": self.metrics.prometheus_text()})
            return
        if op == "export":
            # fleet telemetry (r17): the STRUCTURED metrics export the
            # supervisor's collector scrapes — exact counters,
            # bucket-exact histogram counts, SLO window counts. The
            # fleet plane merges these; it never parses exposition
            # text.
            send({"export": self.metrics.export()})
            return
        if op == "slo":
            # runtime SLO retargeting: {"op": "slo", "ttft_ms": 50,
            # "tpot_ms": 10} sets (resetting the window — attainment
            # against old targets is not attainment against new);
            # omitting both fields just reads the current state. The
            # fleet_goodput bench calibrates targets this way without
            # a replica restart.
            if "ttft_ms" in msg or "tpot_ms" in msg:
                for k in ("ttft_ms", "tpot_ms"):
                    v = msg.get(k)
                    if v is not None and (isinstance(v, bool)
                                          or not isinstance(
                                              v, (int, float))
                                          or v <= 0):
                        send({"error": "BadRequest",
                              "reason": f"{k} must be a positive "
                                        f"number of ms or null"})
                        return
                # an ABSENT key preserves the current target (partial
                # retarget must not silently drop the other SLO); an
                # explicit null clears it
                slo = self.metrics.slo
                slo.set_targets(
                    msg["ttft_ms"] if "ttft_ms" in msg
                    else slo.ttft_ms,
                    msg["tpot_ms"] if "tpot_ms" in msg
                    else slo.tpot_ms)
            send({"slo": {"ttft_ms": self.metrics.slo.ttft_ms,
                          "tpot_ms": self.metrics.slo.tpot_ms,
                          "window_s": self.metrics.slo.window_s,
                          "attainment":
                              self.metrics.slo.attainment()}})
            return
        if op == "trace":
            # r16: finished span trees + tracer annotations + the
            # engine step-timeline ring. format=chrome returns a
            # chrome://tracing JSON mergeable with jax.profiler output
            # via tools/merge_traces.py.
            eng = self.engine
            if msg.get("format") == "chrome":
                send({"chrome": self.tracer.to_chrome()})
                return
            n = msg.get("n")
            if msg.get("drain") is True:
                # consume the finished ring (r17): phase-scoped trace
                # collection — the fleet_goodput bench reads each
                # swept rate's traces without earlier phases bleeding
                # into its attainment computation
                traces = self.tracer.drain()
            else:
                traces = self.tracer.finished(
                    n if isinstance(n, int) and not isinstance(
                        n, bool) else None)
            send({"traces": traces,
                  "events": self.tracer.events(),
                  "step_timeline": getattr(
                      eng, "step_timeline", lambda: [])(),
                  # multi-step decode (r19): macro entries expanded
                  # back into per-token-step rows, and the configured
                  # steps-per-launch (1 = per-token, no macro entries)
                  "multi_step": getattr(eng, "multi_step", 1),
                  "per_token_timeline": getattr(
                      eng, "per_token_timeline", lambda: [])(),
                  "program_costs": getattr(
                      eng, "program_costs", lambda: {})(),
                  "sample_rate": self.tracer.sample_rate})
            return
        if op == "capacity":
            # memory observatory (r18): occupancy + forecast + ledger
            # tail — the capacity/headroom signal the supervisor
            # scrapes per probe cycle and the autoscaler actuator
            # consumes (ROADMAP 3a, memory half)
            send(self._capacity(
                ledger_tail=msg.get("ledger_tail")))
            return
        if op == "profile":
            send(self._profile(msg))
            return
        if op == "drain":
            self.drain()
            send({"ok": True, "status": "draining"})
            return
        if op == "leak_check":
            # answered on the ENGINE thread via the inbox so the audit
            # can't race a step; same outbox plumbing as generate
            pending = _Pending(stream=False)
            self._inbox.put(({"ctl": "leak_check"}, pending))
            self._wake.set()
            self._await_outbox(pending, send)
            return
        if op == "fetch_pages":
            # disaggregated serving (r20): serve chain-page blobs to a
            # peer replica. Keys/heads are hex chain keys; answered on
            # the ENGINE thread (device reads + tier walks must not
            # race a step).
            keys = self._parse_hex_keys(msg.get("keys"))
            heads = self._parse_hex_keys(msg.get("heads"))
            if keys is None or heads is None or not (keys or heads):
                send({"error": "BadRequest",
                      "reason": "fetch_pages needs 'keys' and/or "
                                "'heads' as lists of hex chain keys"})
                return
            try:
                cursor = max(0, int(msg.get("cursor") or 0))
            except (TypeError, ValueError):
                send({"error": "BadRequest",
                      "reason": "fetch_pages cursor must be an int"})
                return
            pending = _Pending(stream=False)
            self._inbox.put(({"ctl": "fetch_pages", "keys": keys,
                              "heads": heads, "cursor": cursor},
                             pending))
            self._wake.set()
            self._await_outbox(pending, send)
            return
        if op == "prefetch":
            # disaggregated serving (r20): pull a PEER's chains into
            # this replica's tiers — the drain-handoff receiving side.
            # The network fetch runs on THIS conn thread (decode never
            # waits on the wire); the tier import lands on the engine
            # thread.
            self._prefetch(msg, send)
            return
        if op == "swap":
            # weight hot-swap (r24): load/validate on THIS conn
            # thread, apply on the engine thread between steps.
            # Allowed while draining — the supervisor's roll path
            # drains a replica, then swaps it.
            self._swap(msg, send)
            return
        if op != "generate":
            send({"error": "BadRequest", "reason": f"unknown op {op!r}"})
            return
        if self._draining:
            send({"error": "ServerDraining",
                  "reason": "server is draining; not admitting"})
            return
        try:
            # per-request fault site: a transient front-end failure is
            # a retryable typed reply, not a dropped connection
            fault_point("serving.request")
        except InjectedFault as e:
            send({"error": "TransientServerError", "reason": str(e),
                  "retryable": True})
            return
        prompt = msg.get("prompt")
        if not isinstance(prompt, list) or not prompt:
            send({"error": "BadRequest",
                  "reason": "prompt must be a non-empty token list"})
            return
        prefill_only = bool(msg.get("prefill_only"))
        if self.role == "prefill" and not prefill_only:
            # prefill-class replicas run admission + chunked prefill
            # only; decode streams belong on a decode/mixed replica
            # (the role-aware router never sends them here)
            send({"error": "WrongRole", "retryable": True,
                  "reason": "replica role is 'prefill'; route decode "
                            "streams through a role-aware router or "
                            "send prefill_only requests"})
            return
        if prefill_only and self.prefix_cache is None:
            send({"error": "BadRequest",
                  "reason": "prefill_only needs a prefix cache to "
                            "park the finished chain in"})
            return
        mnt = int(msg.get("max_new_tokens", 16))
        if prefill_only:
            # the prefill IS the work: one generated token (the
            # minimum submit) proves the chain complete; the reply is
            # a prefill-ack carrying the chain keys, not a stream
            mnt = 1
        if mnt < 1 or mnt > self.max_new_tokens_cap:
            send({"error": "BadRequest",
                  "reason": f"max_new_tokens must be in [1, "
                            f"{self.max_new_tokens_cap}]"})
            return
        prio = msg.get("priority", "normal")
        if prio not in _PRIORITIES:
            send({"error": "BadRequest",
                  "reason": f"priority must be one of "
                            f"{sorted(_PRIORITIES)}"})
            return
        deadline_t = None
        if msg.get("deadline_ms") is not None:
            dl = msg["deadline_ms"]
            # bool is an int subclass: "deadline_ms": true must be a
            # BadRequest, not a surprise 1 ms budget
            if isinstance(dl, bool) or \
                    not isinstance(dl, (int, float)) or dl <= 0:
                send({"error": "BadRequest",
                      "reason": "deadline_ms must be a positive "
                                "number of milliseconds"})
                return
            # the budget starts at ARRIVAL: queueing, prefill, decode
            # and any engine resurrection all spend from it
            deadline_t = time.monotonic() + float(dl) / 1e3
        # disaggregated handoff (r20): a fetch_from hint names the
        # peer holding this prompt's chain — pull its blobs on THIS
        # conn thread before enqueueing (the engine never waits on the
        # wire; a failed fetch is a counted fall-back to local prefill)
        handoff = None
        if not prefill_only and msg.get("fetch_from") is not None:
            # advisory overload pre-check BEFORE the wire pull: a
            # request the depth gate will shed must not first spend
            # up to handoff_timeout_s of peer RPC and churn the spill
            # tiers with an import it never uses. The authoritative
            # gate still runs under the admission lock below.
            check = getattr(self.scheduler, "check_admission", None)
            if check is not None:
                check(self.engine.num_queued + self._inbox.qsize())
            handoff = self._handoff_fetch(msg.get("fetch_from"), prompt)
        pending = _Pending(stream=bool(msg.get("stream", False))
                           and not prefill_only)
        with self._admission_lock:
            # submit-time overload gate, atomic with the enqueue so
            # concurrent connections can't all slip under the depth
            # bound (raises ServerOverloaded -> typed reply upstream)
            check = getattr(self.scheduler, "check_admission", None)
            if check is not None:
                check(self.engine.num_queued + self._inbox.qsize())
            tctx = msg.get("trace")
            if not (isinstance(tctx, dict) and
                    isinstance(tctx.get("id"), str)):
                tctx = None  # malformed/absent: engine sampler decides
            payload = {"prompt": prompt, "max_new_tokens": mnt,
                       "eos": msg.get("eos"),
                       "priority": int(_PRIORITIES[prio]),
                       "deadline_t": deadline_t,
                       "trace_ctx": tctx}
            if prefill_only:
                payload["handoff"] = True
            if handoff is not None:
                payload["_handoff"] = handoff
            self._inbox.put((payload, pending))
        self._wake.set()
        self._await_outbox(pending, send,
                           transform=(self._prefill_ack(prompt)
                                      if prefill_only else None))

    def _await_outbox(self, pending: _Pending, send,
                      transform=None) -> None:
        """Relay one request's outbox to the client until the None
        sentinel (``transform``, when given, rewrites each message —
        the prefill-ack path). Closes the submit-vs-shutdown race: if
        the engine thread has fully EXITED (mere stop() intent is not
        enough — graceful shutdown still finishes in-flight work and
        delivers real results), the request can never complete, so
        answer a typed ServerEvicted instead of hanging."""
        while True:
            try:
                out = pending.outbox.get(timeout=1.0)
            except queue_mod.Empty:
                if self._engine_done.is_set():
                    send({"error": "ServerEvicted",
                          "reason": "server shutting down"})
                    return
                continue
            if out is None:
                return
            send(out if transform is None else transform(out))

    # -- disaggregated serving (r20) ----------------------------------------

    @staticmethod
    def _parse_hex_keys(val) -> Optional[list]:
        """[] for absent, None for malformed, else decoded key bytes."""
        if val is None:
            return []
        if not isinstance(val, list):
            return None
        out = []
        for k in val:
            if not isinstance(k, str):
                return None
            try:
                out.append(bytes.fromhex(k))
            except ValueError:
                return None
        return out

    def _prefill_ack(self, prompt):
        """Reply transform for prefill_only requests: the engine's
        done-reply (tokens included) becomes a prefill-ack naming the
        parked chain — the router hands the KEYS (well, the peer
        address; the decode side re-derives keys from its own prompt)
        to the decode hop. Typed errors pass through untouched."""
        def transform(reply: Dict) -> Dict:
            if not reply.get("done"):
                return reply
            pc = self.prefix_cache
            chain = []
            if pc is not None:
                try:
                    chain = [k.hex() for k in pc.chain_keys_for(
                        np.asarray(prompt, np.int32))]
                except Exception:
                    chain = []
            return {"rid": reply.get("rid"), "done": True,
                    "prefilled": True, "keys": chain,
                    "page_size": self._page_size, "role": self.role,
                    "stats": reply.get("stats")}
        return transform

    def _handoff_fetch(self, ff, prompt) -> Optional[Dict]:
        """Conn-thread wire pull for a generate carrying a
        ``fetch_from`` hint: compute the prompt's chain keys (pure
        hashing), fetch their blobs from the peer, and return the
        bundle the engine thread imports before submit. ANY failure —
        malformed hint, dead peer, typed peer error — is a counted
        fall-back to local prefill (return None), never a hang (the
        socket timeout bounds the wait) and never a client error."""
        pc = self.prefix_cache
        if pc is None or not getattr(pc, "tiers", None) \
                or not isinstance(ff, dict):
            return None
        try:
            host = str(ff.get("host") or self.host)
            port = int(ff["port"])
        except (KeyError, TypeError, ValueError):
            return None
        # cross-generation guard (r24): a hint stamped with a peer
        # generation other than ours is skipped typed-and-counted
        # BEFORE any wire traffic — the peer's pages were computed
        # under different weights and must never splice (the
        # generation-salted chain keys would miss anyway; this makes
        # the skip explicit and free)
        peer_gen = ff.get("generation")
        if peer_gen is not None:
            try:
                peer_gen = int(peer_gen)
            except (TypeError, ValueError):
                return None
            if peer_gen != self._weight_generation:
                self.metrics.counter(
                    "cross_generation_skips_total").add()
                self.tracer.annotate(
                    "handoff_skipped_cross_generation",
                    peer_generation=peer_gen,
                    generation=self._weight_generation)
                return None
        t0 = time.perf_counter()
        try:
            chain = pc.chain_keys_for(np.asarray(prompt, np.int32))
            if not chain:
                return None  # no full shareable block: nothing to pull
            blobs, _missing, nbytes = fetch_page_blobs(
                host, port, keys=chain,
                timeout_s=self.handoff_timeout_s)
        except PageFetchFailed as e:
            self.metrics.counter("handoff_failures_total").add()
            self.tracer.annotate("handoff_fetch_failed",
                                 peer=f"{ff.get('host')}:{ff.get('port')}",
                                 error=str(e)[:200])
            return None
        except Exception as e:
            # chain hashing on a malformed prompt etc: the engine's
            # BadRequest path owns the reply; no handoff
            self.metrics.counter("handoff_failures_total").add()
            self.tracer.annotate("handoff_fetch_failed",
                                 error=f"{type(e).__name__}: {e}"[:200])
            return None
        if not blobs:
            return None  # peer no longer holds the chain: local prefill
        self.metrics.counter("handoff_bytes_total").add(nbytes)
        return {"blobs": blobs, "heads": chain[:1],
                "ms": (time.perf_counter() - t0) * 1e3,
                "bytes": nbytes}

    def _prefetch(self, msg: Dict, send) -> None:
        """The ``prefetch`` op: fetch a peer's chains (by head) into
        this replica's tiers — the drain-handoff receiving side. Fetch
        on this conn thread, import on the engine thread; every
        failure is a typed reply."""
        keys = self._parse_hex_keys(msg.get("keys"))
        heads = self._parse_hex_keys(msg.get("heads"))
        if keys is None or heads is None or not (keys or heads):
            send({"error": "BadRequest",
                  "reason": "prefetch needs 'keys' and/or 'heads' as "
                            "lists of hex chain keys, plus the peer's "
                            "'host'/'port'"})
            return
        pc = self.prefix_cache
        if pc is None or not getattr(pc, "tiers", None):
            send({"error": "PageFetchFailed",
                  "reason": "replica has no spill tier to land "
                            "fetched pages in"})
            return
        try:
            host = str(msg.get("host") or self.host)
            port = int(msg["port"])
        except (KeyError, TypeError, ValueError):
            send({"error": "BadRequest",
                  "reason": "prefetch needs the peer's 'port'"})
            return
        # cross-generation guard (r24): same rule as fetch_from hints
        # — a prefetch stamped with a different weight generation is
        # skipped typed-and-counted, never spliced
        peer_gen = msg.get("generation")
        if peer_gen is not None and not isinstance(peer_gen, bool) \
                and isinstance(peer_gen, int) \
                and peer_gen != self._weight_generation:
            self.metrics.counter("cross_generation_skips_total").add()
            send({"error": "StaleGeneration",
                  "reason": f"prefetch stamped generation {peer_gen} "
                            f"but this replica serves generation "
                            f"{self._weight_generation}; "
                            f"cross-generation pages never splice",
                  "generation": self._weight_generation})
            return
        t0 = time.perf_counter()
        try:
            blobs, missing, nbytes = fetch_page_blobs(
                host, port, keys=keys, heads=heads,
                timeout_s=self.handoff_timeout_s)
        except PageFetchFailed as e:
            self.metrics.counter("handoff_failures_total").add()
            send({"error": "PageFetchFailed", "reason": str(e)})
            return
        self.metrics.counter("handoff_bytes_total").add(nbytes)
        ms = (time.perf_counter() - t0) * 1e3
        pending = _Pending(stream=False)
        self._inbox.put(({"ctl": "import_blobs", "blobs": blobs,
                          "heads": heads}, pending))
        self._wake.set()

        def add_fetch_info(reply: Dict) -> Dict:
            if reply.get("ok"):
                reply = dict(reply)
                reply["fetch_ms"] = round(ms, 3)
                reply["missing"] = missing
            return reply

        self._await_outbox(pending, send, transform=add_fetch_info)

    # -- introspection -----------------------------------------------------

    def _health(self) -> Dict:
        eng = self.engine
        pc = self.prefix_cache
        mesh_info = getattr(eng, "mesh_info", lambda: None)()

        def racy(fn, fallback=-1):
            # conn-thread reads of dicts the engine thread mutates
            # (allocator reservations, prefix-cache books) can hit
            # "dictionary changed size during iteration" under load. A
            # health probe must degrade to a stale/-1 number — a typed
            # RuntimeError reply here reads as a failed probe to the
            # supervisor, which would kill a healthy replica after
            # max_probe_failures of them.
            for _ in range(3):
                try:
                    return fn()
                except RuntimeError:
                    continue
            return fallback

        adv = (racy(lambda: pc.advertised_keys_info(),
                    {"keys": [], "truncated": False})
               if pc is not None else {"keys": [], "truncated": False})
        return {"status": "draining" if self._draining else "ok",
                # autoscaler adoption (r21): a restarted supervisor
                # verifies a journal-recorded replica is really THIS
                # process (not a recycled pid) by matching this
                "pid": _os.getpid(),
                "active": eng.num_active,
                "queued": eng.num_queued,
                # disaggregated serving (r20): the replica's class —
                # the router's role-aware dispatch input
                "role": self.role,
                # cache-affinity routing (r15): the replica's page size
                # plus the chain-head prefix keys it can serve (device
                # entries AND spill-tier blobs) — the FailoverRouter
                # steers keyed requests whose first-block hash matches.
                # truncated=True tells the router "not advertised" may
                # still be resident (r20 satellite: a capped list must
                # not read as a miss)
                "page_size": eng.page_size,
                # weight hot-swap (r24): the generation this replica
                # serves — the supervisor's roll ready-probe and the
                # router's generation-aware affinity read it here
                "weight_generation": self._weight_generation,
                "weight_swaps": getattr(eng, "weight_swaps", 0),
                "prefix_keys": adv["keys"],
                "prefix_keys_truncated": adv["truncated"],
                "free_pages": eng.free_pages,
                "reserved_pages": racy(
                    lambda: eng.allocator.reserved_total),
                "cached_pages": racy(
                    lambda: pc.total_pages()) if pc is not None else 0,
                "num_pages": eng.num_pages,
                "steps": eng.steps,
                # tensor-parallel serving (r10): None = single-device,
                # else {"axes": {...}, "model_parallel": N, ...} — the
                # supervisor and dashboards see the replica's mesh
                # layout without a separate query
                "mesh": mesh_info,
                "engine_restarts": self._restarts,
                # r11 split the EMAs: step_ema_ms stays as the decode
                # alias for existing probes/dashboards
                "step_ema_ms": (None if eng.decode_ema_s is None
                                else round(eng.decode_ema_s * 1e3, 3)),
                "prefill_chunk_ema_ms": (
                    None if eng.prefill_chunk_ema_s is None
                    else round(eng.prefill_chunk_ema_s * 1e3, 3)),
                # chunked prefill: outstanding prefill tokens (half-
                # prefilled slots + queue) and the configured chunk
                "prefill_debt_tokens": eng.prefill_debt_tokens,
                "prefill_chunk_tokens": eng.prefill_chunk_tokens,
                # fused decode hot path (r13): whether the engine
                # traces fused programs, and the per-program traced-op
                # launch counts ({"decode": N, ...} — populated as
                # each program kind first traces)
                "fused_step": getattr(eng, "fused_step", None),
                # multi-step decode (r19): decode steps per launch (1 =
                # per-token) and lifetime macro launches this engine ran
                "multi_step": getattr(eng, "multi_step", 1),
                "macro_launches": getattr(eng, "macro_launches", 0),
                "step_programs": dict(
                    getattr(eng, "step_programs", {}) or {}),
                # end-to-end tracing (r16): the sampling rate and how
                # many span trees the finished ring holds
                "trace_sample": self.tracer.sample_rate,
                "traces_finished": self.tracer.finished_total,
                "uptime_s": round(time.monotonic() - self._t0, 3)}

    def _gauges(self) -> Dict[str, float]:
        """Engine-occupancy gauge source for the Prometheus page
        (serving/metrics.py): live reads of host-side ints — benign
        against the engine thread, same as the health op."""
        eng = self.engine
        pc = self.prefix_cache
        g = {"inflight_slots": eng.num_active,
             # weight hot-swap (r24): the serving generation as a
             # gauge (serving_weight_generation on the scrape page;
             # the supervisor rolls it up per fleet)
             "weight_generation": float(self._weight_generation),
             # num_slots rides along so the fleet plane can compute
             # occupancy (inflight/slots) for the pressure verdict
             "num_slots": eng.num_slots,
             "queued_requests": eng.num_queued,
             "free_pages": eng.free_pages,
             "reserved_pages": eng.allocator.reserved_total,
             "prefix_cache_pages":
                 pc.total_pages() if pc is not None else 0,
             "num_pages": eng.num_pages,
             # chunked prefill (r11): un-stored prompt tokens across
             # half-prefilled slots + the queue — the head-of-line
             # pressure a dashboard watches against TPOT
             "prefill_debt_tokens": eng.prefill_debt_tokens}
        # memory observatory (r18): pool occupancy by owner class —
        # the same breakdown the capacity op and the step-timeline
        # ring carry, scraped into the fleet plane where the pressure
        # verdict's memory input reads it (pages_used/num_pages)
        occ = getattr(eng.allocator, "occupancy", lambda: None)()
        if occ:
            g["pages_inflight"] = occ["inflight"]
            g["pages_prefix_device"] = occ["prefix_device"]
            g["pages_used"] = eng.num_pages - occ["free"]
            # the PRESSURE input: used minus reclaimable-on-demand
            # (refcount-0 cache pages) — a warm inclusive cache fills
            # the pool by design and must not read as exhaustion
            evictable = 0
            if pc is not None:
                try:
                    evictable = int(pc.evictable_pages())
                except RuntimeError:
                    pass  # racy read: skip this scrape's refinement
            g["pages_unreclaimable"] = max(
                0, eng.num_pages - occ["free"] - evictable)
        led = getattr(eng, "ledger", None)
        if led is not None:
            g["ledger_events"] = led.seq
            g["ledger_dropped"] = led.dropped_total
        # hierarchical prefix cache (r15): per-tier occupancy so a
        # dashboard sees how much evicted KV is restorable (bytes and
        # blob counts per spill tier)
        if pc is not None and getattr(pc, "tiers", None):
            for t in pc.tiers:
                g[f"spill_{t.name}_bytes"] = t.occupancy_bytes
                # r23: raw-equivalent bytes of the stored blobs — with
                # a coded blob_format the physical figure undersells
                # restorable KV, so capacity/hit-rate math reads this
                g[f"spill_{t.name}_logical_bytes"] = t.logical_bytes
                g[f"spill_{t.name}_blobs"] = t.blob_count
                g[f"spill_{t.name}_capacity_bytes"] = t.capacity_bytes
        # fused decode (r13): ops traced into the decode-step program
        # (the launch counter) — exported as serving_step_programs so
        # the fused launch-count win is visible on a live server; 0
        # until the decode step first traces
        sp = getattr(eng, "step_programs", None)
        if sp is not None:
            g["step_programs"] = sp.get("decode", 0)
        # step timeline (r16): per-kind program LAUNCH totals, the
        # engine step count, and the latest step's decode wall ms;
        # new entries since the last scrape feed the serving_step_ms
        # histogram (ServingMetrics.step_ms)
        for kind, n in dict(getattr(eng, "programs_launched", {})
                            or {}).items():
            g[f"programs_launched_{kind}"] = n
        g["engine_steps"] = getattr(eng, "steps", 0)
        tl = getattr(eng, "step_timeline", lambda: [])()
        if tl:
            g["step_last_ms"] = tl[-1].get("ms", 0.0)
            g["step_last_decode_ms"] = tl[-1].get("decode_ms", 0.0)
            self._feed_step_histogram(eng, tl)
        # program-cost gauges (r16 satellite): flops / bytes-accessed
        # per program kind from jit cost_analysis at build time
        for kind, cost in getattr(eng, "program_costs",
                                  lambda: {})().items():
            if "flops" in cost:
                g[f"program_{kind}_flops"] = cost["flops"]
                g[f"program_{kind}_bytes_accessed"] = \
                    cost["bytes_accessed"]
        # tracing counters (r16): tracer lifetime totals synced into
        # the registry at scrape (monotonic, so the counter contract
        # holds)
        for cname, val in (
                ("traces_sampled_total", self.tracer.sampled_total),
                ("traces_finished_total", self.tracer.finished_total),
                ("trace_spans_dropped_total",
                 self.tracer.spans_dropped_total)):
            self.metrics.counter(cname).set(val)
        mi = getattr(eng, "mesh_info", lambda: None)()
        if mi is not None:
            # tensor-parallel serving (r10/r16): mesh layout on the
            # scrape page. mesh_collective_bytes was a STUB pinned 0
            # through r15; it now carries the engine's per-decode-step
            # ESTIMATE (ring-allreduce traffic of the row-parallel
            # reductions — see mesh_collective_bytes_estimate, with
            # the per-program flops/bytes from cost_analysis exported
            # above). The chip-MEASURED value still needs an on-chip
            # profiler session (xprof collective stats) — chip-pending,
            # same convention as the BENCH_STAGED cpu_smoke markers.
            g["mesh_model_parallel"] = mi["model_parallel"]
            g["mesh_devices"] = mi["devices"]
            est = getattr(eng, "mesh_collective_bytes_estimate",
                          lambda: None)()
            g["mesh_collective_bytes"] = est if est is not None else 0.0
        return g

    def _feed_step_histogram(self, eng, tl) -> None:
        """Observe ring entries newer than the last scrape into the
        serving_step_ms histogram. The marker keys on the RESTART
        COUNT (monotonic) — id(eng) could be reused by a later engine
        allocated at a freed one's address, silently inheriting a
        stale high-water step."""
        key, seen = self._tl_seen
        if key != self._restarts:
            key, seen = self._restarts, -1
        for entry in tl:
            s = entry.get("step", 0)
            if s > seen:
                self.metrics.step_ms.observe(entry.get("ms", 0.0))
                # multi-step decode (r19): a boundary entry carrying a
                # drained macro launch feeds the steps-per-launch and
                # host-overlap-idle distributions
                macro = entry.get("macro")
                if macro:
                    self.metrics.steps_per_launch.observe(
                        float(macro.get("steps", 0)))
                    self.metrics.host_overlap_idle_ms.observe(
                        float(macro.get("overlap_idle_ms", 0.0)))
                seen = s
        self._tl_seen = (key, seen)
        # macro-launch counter: accumulate engine deltas per restart
        # epoch (a rebuilt engine starts its counter at 0)
        ml = int(getattr(eng, "macro_launches", 0) or 0)
        mkey, mseen = self._macro_seen
        if mkey != self._restarts:
            mkey, mseen = self._restarts, 0
        if ml > mseen:
            self.metrics.counter("macro_steps_total").add(ml - mseen)
            mseen = ml
        self._macro_seen = (mkey, mseen)

    # max chain pages served per fetch_pages reply: bounds one reply's
    # size (a page blob is small — page*H*D*2*itemsize per layer — but
    # an unbounded key list would let one peer RPC occupy the engine
    # thread arbitrarily long between steps)
    FETCH_PAGES_CAP = 512

    def _serve_fetch_pages(self, payload: Dict) -> Dict:
        """Engine-thread half of the ``fetch_pages`` wire op (r20):
        expand requested chain heads, pack device-resident pages /
        read tier blobs, and base64 them for the reply. A key this
        replica cannot produce is listed in ``missing`` — the peer's
        chained-prefill fallback covers it, so this op never errors
        on absence.

        Cursor pagination (r23): each reply serves at most
        FETCH_PAGES_CAP keys starting at ``payload["cursor"]`` (an
        offset into the deterministic expanded key list) and carries
        ``next_cursor`` while more remain — so a chain longer than
        one page's cap hands off WHOLE across several bounded RPCs
        instead of silently degrading its tail to missing. The
        legacy ``truncated`` flag stays for pre-r23 clients."""
        import base64
        pc = self.prefix_cache
        if pc is None:
            return {"error": "PageFetchFailed",
                    "reason": "replica has no prefix cache"}
        keys = list(payload.get("keys") or ())
        heads = list(payload.get("heads") or ())
        if heads:
            seen = set(keys)
            keys += [k for k in pc.expand_heads(heads)
                     if k not in seen]
        cursor = max(0, int(payload.get("cursor") or 0))
        window = keys[cursor:cursor + self.FETCH_PAGES_CAP]
        remaining = len(keys) - (cursor + len(window))
        truncated = len(keys) > self.FETCH_PAGES_CAP
        blobs, missing = pc.export_blobs(window)
        reply = {"blobs": {k.hex(): base64.b64encode(b).decode("ascii")
                           for k, b in blobs.items()},
                 "missing": [k.hex() for k in missing],
                 "count": len(blobs),
                 "bytes": sum(len(b) for b in blobs.values()),
                 "truncated": truncated,
                 "role": self.role,
                 # r24: the generation these blobs were computed under
                 # (cross-generation requests miss by key construction;
                 # this makes the provenance explicit on the wire)
                 "generation": self._weight_generation}
        if remaining > 0:
            reply["next_cursor"] = cursor + len(window)
        return reply

    def _import_blobs(self, payload: Dict) -> Dict:
        """Engine-thread half of the ``prefetch`` op (r20 drain
        handoff): land already-fetched blobs in the cache tiers (crc
        re-verified per blob by ``import_blobs``)."""
        pc = self.prefix_cache
        if pc is None or not getattr(pc, "tiers", None):
            return {"error": "PageFetchFailed",
                    "reason": "replica has no spill tier to land "
                              "fetched pages in"}
        rep = pc.import_blobs(payload.get("blobs") or {},
                              heads=payload.get("heads") or ())
        rep["ok"] = True
        return rep

    def _leak_check(self) -> Dict:
        """Engine-thread page audit: with no in-flight work, the
        allocator must balance (cache-less: everything free; cached:
        free + cache-owned == pool, no other owners). The reply also
        carries the page-ledger RECONCILIATION (r18): the event-derived
        ownership shadow must match the allocator's books exactly —
        the chaos harness's invariant 5."""
        eng = self.engine
        led = getattr(eng, "ledger", None)
        ledger_info = ({"ok": True, "enabled": False} if led is None
                       else led.reconcile(eng.allocator))
        if eng.num_active or eng.num_queued:
            return {"ok": False, "busy": True,
                    "active": eng.num_active, "queued": eng.num_queued}
        try:
            if self.prefix_cache is not None:
                self.prefix_cache.check_consistent(eng.allocator)
            else:
                eng.allocator.check_no_leak()
        except Exception as e:
            return {"ok": False, "busy": False,
                    "error": type(e).__name__, "reason": str(e),
                    "ledger": ledger_info}
        return {"ok": True, "busy": False,
                "free_pages": eng.free_pages,
                "reserved_pages": eng.allocator.reserved_total,
                "cached_pages": (self.prefix_cache.total_pages()
                                 if self.prefix_cache is not None else 0),
                "num_pages": eng.num_pages,
                "ledger": ledger_info}

    def _capacity(self, ledger_tail=None) -> Dict:
        """The ``capacity`` op payload: the engine's occupancy card,
        an EWMA exhaustion forecast over step-timeline ring deltas,
        and (on request) the ledger ring tail. Conn-thread reads of
        host ints/dicts — the same benign-race contract as health."""
        from ..inference.page_ledger import forecast_exhaustion
        eng = self.engine
        snap = getattr(eng, "capacity_snapshot", lambda: {})()
        snap["forecast"] = forecast_exhaustion(
            getattr(eng, "step_timeline", lambda: [])())
        n = ledger_tail
        if isinstance(n, int) and not isinstance(n, bool) and n > 0:
            snap["ledger_tail"] = getattr(
                eng, "ledger_tail", lambda _n: [])(n)
        snap["engine_restarts"] = self._restarts
        return snap

    def _profile(self, msg: Dict) -> Dict:
        """The ``profile`` op (r18): live per-device HBM accounting
        plus an optional ``jax.profiler`` device capture window. The
        capture runs on THIS connection thread while the engine thread
        keeps stepping, so the dump holds real serving programs (the
        jit bodies' pt.* named_scopes); ``{"ms": N, "dir": PATH}``
        captures N ms into PATH (tensorboard layout — the
        *.trace.json.gz inside merges with span dumps via
        tools/merge_traces.py). One capture at a time: a concurrent
        request gets a typed ProfileBusy, never a corrupted trace."""
        import jax
        out: Dict[str, Any] = {"devices": [], "chip_pending": True}
        for d in jax.devices():
            stats = None
            fn = getattr(d, "memory_stats", None)
            if callable(fn):
                try:
                    raw = fn()
                    if raw:
                        stats = {str(k): int(v)
                                 for k, v in raw.items()
                                 if isinstance(v, (int, float))}
                except Exception:
                    stats = None
            if stats:
                # a backend that accounts HBM makes the gauges real;
                # CPU reports none — the numbers stay chip-pending
                out["chip_pending"] = False
            out["devices"].append({"id": int(d.id),
                                   "platform": str(d.platform),
                                   "memory_stats": stats})
        ms = msg.get("ms")
        if ms is not None:
            if isinstance(ms, bool) or not isinstance(ms, (int, float)) \
                    or ms <= 0 or ms > 30_000:
                return {"error": "BadRequest",
                        "reason": "ms must be a capture window in "
                                  "(0, 30000] milliseconds"}
            if not self._profile_lock.acquire(blocking=False):
                return {"error": "ProfileBusy",
                        "reason": "a profiler capture is already "
                                  "running; retry after it finishes"}
            try:
                import tempfile
                trace_dir = msg.get("dir") or tempfile.mkdtemp(
                    prefix="pt-profile-")
                jax.profiler.start_trace(trace_dir)
                try:
                    time.sleep(float(ms) / 1e3)
                finally:
                    jax.profiler.stop_trace()
                out["trace_dir"] = trace_dir
                out["ms"] = float(ms)
            except Exception as e:
                return {"error": "ProfileFailed",
                        "reason": f"{type(e).__name__}: {e}"}
            finally:
                self._profile_lock.release()
        return out

    def _cache_stats(self) -> Optional[Dict]:
        pc = self.prefix_cache
        if pc is None:
            return None
        return {"pages": pc.total_pages(), "hit_pages": pc.hit_pages,
                "miss_pages": pc.miss_pages,
                "inserted_pages": pc.inserted_pages,
                "evicted_pages": pc.evicted_pages,
                "hit_rate": pc.hit_rate(),
                # hierarchical tiers (r15): per-tier hit/occupancy
                # breakdown plus spill/restore lifetime counters
                "tiers": pc.tier_stats(),
                "spilled_pages": pc.spilled_pages,
                "restored_pages": pc.restored_pages,
                "restore_corrupt": pc.restore_corrupt,
                "spill_failed": pc.spill_failed,
                # disaggregated handoff (r20): blobs served to /
                # accepted from peer replicas over fetch_pages
                "exported_pages": getattr(pc, "exported_pages", 0),
                "imported_pages": getattr(pc, "imported_pages", 0),
                "import_corrupt": getattr(pc, "import_corrupt", 0),
                # KV byte substrate (r23): transport codec + dedup
                # accounting. codec_stats is non-empty only on a lossy
                # blob_format — max_abs_err is the REPORTED accuracy
                # delta, never silent
                "blob_format": getattr(pc, "blob_format", "raw"),
                "dedup": getattr(pc, "dedup", False),
                "dedup_hits": getattr(pc, "dedup_hits", 0),
                "codec_stats": dict(getattr(pc, "codec_stats", {}))}


def _json_stats(stats) -> Dict:
    out = stats.to_dict()
    return {k: (round(v, 6) if isinstance(v, float) else v)
            for k, v in out.items() if v is not None}


def client_request(host: str, port: int, payload: Dict,
                   timeout_s: float = 120.0, on_token=None) -> Dict:
    """Minimal blocking client: send one request, collect streamed
    tokens through ``on_token(token)``, return the final reply."""
    with socket.create_connection((host, port), timeout=timeout_s) as s:
        rfile = s.makefile("r", encoding="utf-8")
        wfile = s.makefile("w", encoding="utf-8")
        wfile.write(json.dumps(payload) + "\n")
        wfile.flush()
        for line in rfile:
            msg = json.loads(line)
            if "token" in msg:  # streamed chunk (its "done" flag marks
                if on_token is not None:  # the LAST token, not the
                    on_token(msg["token"])  # final summary message)
                continue
            return msg  # final reply: summary, admin reply, or error
    raise ConnectionError("server closed the connection mid-request")


def _build_model(name: str):
    import paddle_tpu as pt
    from ..models.gpt import (GPTForCausalLM, gpt_125m, gpt_1p3b,
                              gpt_350m, gpt_tiny)
    configs = {"gpt_tiny": gpt_tiny, "gpt_125m": gpt_125m,
               "gpt_350m": gpt_350m, "gpt_1p3b": gpt_1p3b}
    if name not in configs:
        raise SystemExit(f"unknown --model {name!r}; choose from "
                         f"{sorted(configs)}")
    pt.seed(0)
    model = GPTForCausalLM(configs[name]())
    model.eval()
    return model


def main(argv=None) -> None:
    import argparse
    parser = argparse.ArgumentParser(
        description="paddle_tpu serving front-end (newline-JSON)")
    parser.add_argument("--model", default="gpt_125m")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8765)
    parser.add_argument("--num-slots", type=int, default=4)
    parser.add_argument("--page-size", type=int, default=64)
    parser.add_argument("--num-pages", type=int, default=None)
    parser.add_argument("--max-seq-len", type=int, default=None)
    parser.add_argument("--no-prefix-cache", action="store_true")
    parser.add_argument(
        "--role", default="mixed", choices=list(_ROLES),
        help="disaggregated serving (r20): 'mixed' (default) is the "
             "full replica, byte-for-byte the pre-r20 behavior. "
             "'prefill' runs admission + (chunked) prefill only — it "
             "answers prefill_only requests, parks finished KV chains "
             "in its cache/spill tiers, advertises them via health "
             "prefix_keys, and serves them to peers over the "
             "fetch_pages op (plain generates get a typed WrongRole). "
             "'decode' serves token streams and, when the router "
             "supplies a fetch_from hint, pulls the prompt's chain "
             "from the prefill peer and splices it in instead of "
             "re-prefilling (greedy outputs bit-identical either "
             "way). Non-mixed roles default a 64 MB host spill tier "
             "when none is configured")
    parser.add_argument(
        "--handoff-timeout-s", type=float, default=30.0, metavar="S",
        help="socket timeout of cross-replica fetch_pages pulls; on "
             "expiry the request falls back to local prefill typed "
             "(PageFetchFailed is counted, never a hang)")
    parser.add_argument(
        "--spill-mb", type=int, default=None, metavar="MB",
        help="hierarchical prefix cache (r15): add a host-RAM spill "
             "tier of this many MB — refcount-0 prefix pages evicted "
             "from the device pool are kept as content-hashed blobs "
             "and restored on a later hit via one device_put + "
             "page-table splice instead of a re-prefill (greedy "
             "outputs stay bit-identical; default: evictions are "
             "dropped)")
    parser.add_argument(
        "--spill-dir", default=None, metavar="DIR",
        help="add a disk spill tier under DIR behind the host tier "
             "(host-tier LRU evictions demote here; blobs are "
             "crc32-checked on restore and scrubbed on shutdown)")
    parser.add_argument(
        "--spill-disk-mb", type=int, default=1024, metavar="MB",
        help="byte budget of the --spill-dir disk tier (default 1024)")
    parser.add_argument(
        "--max-engine-errors", type=int, default=32,
        help="consecutive engine-step failures before the engine is "
             "resurrected (torn down, rebuilt, in-flight replayed)")
    parser.add_argument(
        "--max-engine-restarts", type=int, default=2,
        help="engine resurrections before the server gives up and "
             "fails everything with a typed EngineFailed")
    parser.add_argument(
        "--stall-timeout-s", type=float, default=None,
        help="evict a slot that emits no token for this long with a "
             "typed RequestStalled reply (default: watchdog off)")
    parser.add_argument(
        "--prefill-chunk", type=int, default=None, metavar="TOKENS",
        help="chunked prefill: admit long prompts without stalling "
             "in-flight streams by prefilling at most this many "
             "page-aligned tokens per decode step (must be a multiple "
             "of --page-size; default: whole-prompt prefill). Greedy "
             "outputs stay bit-identical; smaller chunks protect "
             "interactive TPOT, larger chunks finish batch prefills "
             "sooner")
    parser.add_argument(
        "--multi-step", type=int, default=1, metavar="N",
        help="device-resident multi-step decode (r19): run N decode "
             "steps per device program launch (one on-device "
             "early-exit loop with a [slots, N] token ring read back "
             "once per launch), overlapping host scheduling with "
             "device compute. 1 (the default) is the per-token "
             "engine, byte-for-byte. Greedy outputs are bit-identical "
             "for any N; larger N cuts host launch overhead per token "
             "but coarsens admission/chunked-prefill boundaries (new "
             "requests wait up to N steps), so keep N small when "
             "TTFT matters")
    parser.add_argument(
        "--no-fused-step", action="store_true",
        help="disable the fused decode hot path (r13: attention + "
             "out-projection folded into one kernel, sampling streamed "
             "through the lm_head so [B, vocab] logits never hit HBM). "
             "The fused path is the default; greedy outputs are "
             "bit-identical either way on the CPU reference lane "
             "(on-chip Mosaic-kernel parity is chip-pending "
             "validation), and this escape hatch restores the "
             "byte-for-byte pre-r13 programs")
    parser.add_argument(
        "--speculate", type=int, default=0, metavar="K",
        help="draft K tokens per decode step and verify them in one "
             "forward (0 = off); greedy outputs stay bit-identical")
    parser.add_argument(
        "--draft-model", default="ngram",
        help="draft source for --speculate: 'ngram' (prompt lookup, "
             "no second model) or a model name (e.g. gpt_tiny)")
    parser.add_argument(
        "--draft-window", type=int, default=64,
        help="context window of a --draft-model draft")
    parser.add_argument(
        "--mesh", default=None, metavar="model=N",
        help="tensor-parallel serving mesh: shard weights and KV "
             "pages over N devices along the model axis "
             "(distributed/topology.py make_serving_mesh). Greedy "
             "outputs stay bit-identical to the single-device engine; "
             "omit for the single-device default")
    parser.add_argument(
        "--trace-sample", type=float, default=0.0, metavar="R",
        help="end-to-end request tracing (r16): sample this fraction "
             "of requests into span trees (queue -> admit -> prefill "
             "chunks -> decode steps -> complete, stitched across "
             "resurrection/failover). 0 = off (the default; tracing "
             "off costs ~zero on the hot path), 1.0 = every request. "
             "Dump via the 'trace' op; greedy outputs are "
             "bit-identical tracing on/off")
    parser.add_argument(
        "--slo-ttft-ms", type=float, default=None, metavar="MS",
        help="fleet telemetry (r17): TTFT target for the live "
             "SLO-attainment monitor — the rolling-window fraction of "
             "finished requests meeting it surfaces per class as "
             "serving_slo_attainment gauges and in the supervisor's "
             "fleet_stats (retargetable at runtime via the 'slo' op)")
    parser.add_argument(
        "--slo-tpot-ms", type=float, default=None, metavar="MS",
        help="TPOT target for the live SLO monitor (see --slo-ttft-ms)")
    parser.add_argument(
        "--slo-window-s", type=float, default=120.0, metavar="S",
        help="rolling window of the live SLO monitor (default 120)")
    parser.add_argument(
        "--flight-dir", default=None, metavar="DIR",
        help="crash flight recorder (r17): write black-box bundles "
             "(step-timeline ring, sampled traces, metrics export, "
             "inflight dump, engine recipe) to DIR on engine "
             "resurrection, terminal EngineFailed, or a stalled "
             "request — atomic tmp+rename writes, byte-budgeted "
             "retention; inspect with tools/flight_inspect.py")
    parser.add_argument(
        "--flight-budget-mb", type=int, default=64, metavar="MB",
        help="retention byte budget of --flight-dir (oldest bundles "
             "pruned first, the newest always kept; default 64)")
    parser.add_argument(
        "--no-page-ledger", action="store_true",
        help="disable the page ledger (r18: every page event appended "
             "to a bounded ring with owner/step/reason — leak "
             "forensics, ledger reconciliation, capacity-op event "
             "tail). On by default at ~1.0x ms/step; greedy outputs "
             "are bit-identical either way")
    parser.add_argument(
        "--blob-format", default="raw", choices=["raw", "int8", "int4"],
        help="KV byte substrate (r23): transport codec for spill/"
             "handoff/prefetch page blobs. 'raw' (default) is the r22 "
             "byte layout. 'int8' moves ~2x fewer bytes — LOSSLESS "
             "(bit-identical greedy) when the engine already runs "
             "int8 KV pages, the pinned quantize_kv round trip when "
             "it runs float pages. 'int4' moves ~4x fewer bytes and "
             "is always lossy (pinned nibble decode). Lossy formats "
             "report their max_abs_err in cache_stats codec_stats — "
             "the accuracy delta is never silent")
    parser.add_argument(
        "--no-dedup", action="store_true",
        help="disable cross-request page dedup (r23: content-identical "
             "FULL pages from unrelated requests fold onto one "
             "physical page, proven by the chained blake2b keys; "
             "greedy outputs are bit-identical on/off). "
             "--blob-format raw plus --no-dedup restores the r22 "
             "byte layout exactly")
    parser.add_argument(
        "--checkpoint", default=None, metavar="DIR",
        help="weight hot-swap (r24): boot from the newest valid "
             "checkpoint under DIR (ResilientCheckpointManager "
             "manifest layout, crc-validated) instead of the seeded "
             "init — how replicas (re)spawned mid-roll join the fleet "
             "on the rolled weights. Swap a LIVE replica via the "
             "'swap' op; a corrupt/missing checkpoint fails startup "
             "typed")
    parser.add_argument(
        "--weight-generation", type=int, default=0, metavar="N",
        help="weight generation this replica serves (salts the KV "
             "chain keys so pages from other generations miss by "
             "construction; the supervisor threads it through "
             "respawns so a re-role never reverts a rolled replica)")
    parser.add_argument(
        "--forecast-admission", action="store_true",
        help="byte-planning admission (r23): _fits also charges the "
             "fleet's forecast page burn (r18 EWMA exhaustion "
             "forecast) over the request's expected lifetime, so a "
             "request lands only when the pool's FUTURE accommodates "
             "it (default: instant-occupancy gate only)")
    args = parser.parse_args(argv)

    model = _build_model(args.model)
    speculative = None
    if args.speculate > 0:
        from ..inference import SpeculativeConfig
        draft = args.draft_model
        if draft != "ngram":
            draft = _build_model(draft)
        speculative = SpeculativeConfig(k=args.speculate, draft=draft,
                                        draft_window=args.draft_window)
    engine_kwargs = {}
    if args.num_pages is not None:
        engine_kwargs["num_pages"] = args.num_pages
    if args.max_seq_len is not None:
        engine_kwargs["max_seq_len"] = args.max_seq_len
    if args.prefill_chunk is not None:
        # rides in engine_kwargs, so the resurrection recipe rebuilds
        # a chunked engine too
        engine_kwargs["prefill_chunk_tokens"] = args.prefill_chunk
    if args.no_fused_step:
        # rides in engine_kwargs, so a resurrected engine honors the
        # escape hatch too (fused is the engine default)
        engine_kwargs["fused_step"] = False
    if args.multi_step != 1:
        # rides in engine_kwargs -> the resurrection recipe, so a
        # rebuilt engine keeps the macro-launch cadence (and replays
        # bit-identically onto it)
        engine_kwargs["multi_step"] = args.multi_step
    if args.no_page_ledger:
        engine_kwargs["page_ledger"] = False
    if args.forecast_admission:
        # rides in engine_kwargs, so a resurrected engine keeps the
        # byte-planning admission gate
        engine_kwargs["forecast_admission"] = True
    mesh_desc = "single-device"
    if args.mesh is not None:
        from ..distributed.topology import (make_serving_mesh,
                                            parse_mesh_spec)
        try:
            mp = parse_mesh_spec(args.mesh)
            # mesh= rides in engine_kwargs, so the resurrection recipe
            # (ServingServer._build_engine) rebuilds onto the SAME mesh
            engine_kwargs["mesh"] = make_serving_mesh(mp)
        except ValueError as e:
            raise SystemExit(f"--mesh: {e}")
        mesh_desc = f"mesh model={mp}"
    server = ServingServer(model, host=args.host, port=args.port,
                           prefix_cache=not args.no_prefix_cache,
                           role=args.role,
                           handoff_timeout_s=args.handoff_timeout_s,
                           blob_format=args.blob_format,
                           dedup=not args.no_dedup,
                           checkpoint=args.checkpoint,
                           weight_generation=args.weight_generation,
                           num_slots=args.num_slots,
                           page_size=args.page_size,
                           max_engine_errors=args.max_engine_errors,
                           max_engine_restarts=args.max_engine_restarts,
                           stall_timeout_s=args.stall_timeout_s,
                           spill_bytes=(None if args.spill_mb is None
                                        else args.spill_mb << 20),
                           spill_dir=args.spill_dir,
                           spill_disk_bytes=(
                               None if args.spill_dir is None
                               else args.spill_disk_mb << 20),
                           trace_sample=args.trace_sample,
                           slo_ttft_ms=args.slo_ttft_ms,
                           slo_tpot_ms=args.slo_tpot_ms,
                           slo_window_s=args.slo_window_s,
                           flight_dir=args.flight_dir,
                           flight_budget_bytes=(
                               args.flight_budget_mb << 20),
                           speculative=speculative, **engine_kwargs)
    port = server.start()
    print(f"[paddle_tpu.serving] listening on {args.host}:{port} "
          f"(model {args.model}, {mesh_desc}); newline-JSON, see "
          f"module docstring", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("[paddle_tpu.serving] draining ...", flush=True)
        server.stop()


if __name__ == "__main__":
    main()
