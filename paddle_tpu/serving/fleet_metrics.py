"""Fleet telemetry plane (r17): the supervisor-side metrics tier.

PR 10 gave one replica deep eyes (span trees, step timeline, goodput
bench); this module is the layer that makes N replicas observable as
ONE deployment, the way the compiler tier's named-axis meshes scale
without code changes — the serving tier gets a telemetry tier that
scales with replica count without the operator scraping N ports:

- **Collector** (`FleetMetrics`): the supervisor's monitor loop
  already probes every replica; a healthy probe now also scrapes the
  replica's STRUCTURED metrics export (``{"op": "export"}`` →
  ``ServingMetrics.export()``: exact counters, bucket-exact histogram
  counts, SLO window counts — never parsed exposition text). Exports
  merge bucket-exactly (serving/metrics.py ``merge_exports``): fleet
  ``_count``/``_sum``/``_bucket`` equal the SUM of replica exports,
  and fleet quantiles are interpolated from the merged buckets (the
  per-replica reservoirs deliberately don't travel — samples don't
  merge, fixed buckets do). A replica that dies mid-scrape keeps its
  last export, marked STALE, and stale exports are DROPPED from the
  fleet rollup — a dead replica never poisons fleet totals.

- **Live SLO monitor**: per-class rolling-window attainment
  (serving/metrics.py ``SLOAttainment``, targets from the server's
  ``--slo-ttft-ms``/``--slo-tpot-ms``) merged across replicas by
  summing window counts, plus queue-depth/prefill-debt pressure
  signals and a machine-readable ``pressure`` verdict
  (``scale_up``/``steady``/``scale_down`` with hysteresis) — the
  exact input contract ROADMAP 3(a)'s autoscaler will consume, landed
  here telemetry-only (no actuator).

- **Outlier detection**: per-replica step-ms / TPOT / error-rate over
  the most recent scrape window (DELTAS between consecutive exports,
  so a replica's bad last minute isn't averaged away by its good
  hour) compared against the fleet median via MAD-based robust
  z-scores. Flagged replicas surface in ``fleet_stats`` and a
  counter; the router can optionally (default off) deprioritize them
  for unkeyed traffic.

- **Crash flight recorder** (`FlightRecorder`): on engine
  resurrection, terminal EngineFailed, or a stalled-request eviction,
  the server writes a black-box bundle — step-timeline ring, finished
  sampled traces, metrics export, in-flight dump, engine recipe —
  with atomic tmp+rename and a byte-budgeted retention ring, so a
  postmortem no longer depends on having had stderr attached.
  ``tools/flight_inspect.py`` lints and pretty-prints bundles.

Everything here is HOST-side bookkeeping over numbers the replicas
already compute: greedy outputs are bit-identical with the plane on
or off, and the scrape cost is one extra RPC per replica per probe
cycle (the fleet_goodput bench A/Bs it at ~1.0x ms/step).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .metrics import (attainment_from_export, export_snapshot,
                      merge_exports)

__all__ = ["FleetMetrics", "ReplicaTelemetry", "PressureMonitor",
           "FlightRecorder", "merge_slo_exports", "robust_zscores",
           "prometheus_export_lines", "prometheus_multi_export_lines"]


# ---------------------------------------------------------------------------
# merge helpers
# ---------------------------------------------------------------------------


def merge_slo_exports(exports: List[Dict]) -> Dict:
    """Fold N ``SLOAttainment.export()`` dicts into one: per-class
    window counts sum (counts are counts — the fleet attainment over
    the union window is exact). Targets are taken from the first
    export that has them; replicas are expected to share targets (the
    supervisor forwards one CLI), and a disagreeing replica's counts
    still merge — attainment is evaluated replica-side against ITS
    targets, which is the honest reading of a mid-rollout fleet."""
    merged: Dict[str, Any] = {"ttft_ms": None, "tpot_ms": None,
                              "window_s": None, "classes": {}}
    for e in exports:
        if not e:
            continue
        for k in ("ttft_ms", "tpot_ms", "window_s"):
            if merged[k] is None and e.get(k) is not None:
                merged[k] = e[k]
        for cls, c in (e.get("classes") or {}).items():
            m = merged["classes"].setdefault(
                cls, {"total": 0, "ttft_met": 0, "tpot_met": 0,
                      "met": 0})
            for f in m:
                m[f] += int(c.get(f, 0))
    return merged


def _merge_fresh_exports(fresh: List["ReplicaTelemetry"]) -> Dict:
    """One merged fleet view over the FRESH replicas: summed
    counters, summed numeric gauges, bucket-exact histogram merges
    (a ladder mismatch becomes an ``{"error": ...}`` entry), and the
    summed SLO window. The single merge path both ``fleet_snapshot``
    and the Prometheus exposition read — they can't drift apart."""
    counters: Dict[str, int] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, Dict] = {}
    for rt in fresh:
        for k, v in (rt.export.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + int(v)
        for k, v in (rt.export.get("gauges") or {}).items():
            if isinstance(v, (int, float)):
                gauges[k] = gauges.get(k, 0.0) + float(v)
    for name in sorted({h for rt in fresh
                        for h in (rt.export.get("histograms")
                                  or {})}):
        try:
            hists[name] = merge_exports(
                [(rt.export.get("histograms") or {}).get(name)
                 for rt in fresh])
        except ValueError as e:
            hists[name] = {"error": str(e)}
    slo = merge_slo_exports([(rt.export.get("slo") or {})
                             for rt in fresh])
    return {"counters": counters, "gauges": gauges,
            "histograms": hists, "slo": slo}


def robust_zscores(values: Dict[int, float]) -> Dict[int, float]:
    """MAD-based robust z-score per replica: (x - median) / (1.4826 *
    MAD). With MAD == 0 (identical replicas — the common healthy
    case) every score is 0 unless a value differs from the median at
    all, in which case it falls back to a median-relative ratio so a
    single wildly-slow replica among identical peers is still caught.
    Fewer than 3 values -> all zeros (no meaningful median)."""
    if len(values) < 3:
        return {k: 0.0 for k in values}
    xs = sorted(values.values())
    n = len(xs)
    med = (xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2]))
    devs = sorted(abs(v - med) for v in values.values())
    mad = (devs[n // 2] if n % 2
           else 0.5 * (devs[n // 2 - 1] + devs[n // 2]))
    out = {}
    for k, v in values.items():
        if mad > 1e-12:
            out[k] = (v - med) / (1.4826 * mad)
        elif abs(v - med) <= 1e-12:
            out[k] = 0.0
        else:
            # degenerate spread: every other replica identical. Scale
            # by the median so "2x the fleet" reads as a big score.
            scale = max(abs(med), 1e-9)
            out[k] = (v - med) / scale * 10.0
    return out


# ---------------------------------------------------------------------------
# pressure verdict (the 3(a) autoscaler input contract, telemetry-only)
# ---------------------------------------------------------------------------


class PressureMonitor:
    """Hysteretic scale hint from fleet load + SLO attainment.

    Raw verdict per evaluation:

    - ``scale_up``   — SLO attainment (when targets are configured)
      below ``attain_low``, OR mean queued requests per live replica
      above ``queue_high``, OR prefill debt per replica above
      ``debt_high`` tokens, OR fleet page-pool utilization above
      ``mem_high`` (the r18 memory input: a fleet meeting every
      latency SLO still needs replicas BEFORE its KV pool exhausts —
      the missing half of the 3(a) actuator contract);
    - ``scale_down`` — attainment at/above ``attain_high`` (or no
      targets), near-empty queues (< ``queue_low``), slot occupancy
      below ``occupancy_low``, AND memory comfortably below
      ``mem_high``;
    - ``steady``     — anything else.

    The PUBLISHED verdict only flips after ``hysteresis`` consecutive
    identical raw verdicts — a single bursty scrape must not flap the
    hint an autoscaler acts on. This is the signal plane of ROADMAP
    3(a); the actuator (actually changing replica count) is a later
    PR."""

    def __init__(self, attain_low: float = 0.9,
                 attain_high: float = 0.98,
                 queue_high: float = 4.0, queue_low: float = 0.5,
                 debt_high: float = 4096.0,
                 occupancy_low: float = 0.25, hysteresis: int = 3,
                 mem_high: float = 0.92):
        self.attain_low = float(attain_low)
        self.attain_high = float(attain_high)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.debt_high = float(debt_high)
        self.occupancy_low = float(occupancy_low)
        self.hysteresis = max(1, int(hysteresis))
        self.mem_high = float(mem_high)
        self.verdict = "steady"
        self._raw = "steady"
        self._streak = 0

    def _raw_verdict(self, attainment: Optional[float],
                     queued_per_replica: float,
                     debt_per_replica: float,
                     occupancy: Optional[float],
                     mem_utilization: Optional[float] = None) -> str:
        missed = attainment is not None and attainment < self.attain_low
        mem_pressed = (mem_utilization is not None
                       and mem_utilization > self.mem_high)
        if (missed or mem_pressed
                or queued_per_replica > self.queue_high
                or debt_per_replica > self.debt_high):
            return "scale_up"
        attained = attainment is None or attainment >= self.attain_high
        idle = (queued_per_replica < self.queue_low
                and (occupancy is None
                     or occupancy < self.occupancy_low)
                and (mem_utilization is None
                     or mem_utilization <= self.mem_high))
        if attained and idle:
            return "scale_down"
        return "steady"

    def evaluate(self, attainment: Optional[float],
                 queued_per_replica: float,
                 debt_per_replica: float,
                 occupancy: Optional[float],
                 mem_utilization: Optional[float] = None
                 ) -> Dict[str, Any]:
        raw = self._raw_verdict(attainment, queued_per_replica,
                                debt_per_replica, occupancy,
                                mem_utilization)
        if raw == self._raw:
            self._streak += 1
        else:
            self._raw, self._streak = raw, 1
        if raw == self.verdict:
            # streak toward the current verdict just re-confirms it
            self._streak = min(self._streak, self.hysteresis)
        elif self._streak >= self.hysteresis:
            self.verdict = raw
        return {"verdict": self.verdict, "raw": raw,
                "streak": self._streak,
                "hysteresis": self.hysteresis,
                "inputs": {"attainment": attainment,
                           "queued_per_replica":
                               round(queued_per_replica, 3),
                           "debt_per_replica":
                               round(debt_per_replica, 1),
                           "occupancy": (None if occupancy is None
                                         else round(occupancy, 3)),
                           "mem_utilization": (
                               None if mem_utilization is None
                               else round(mem_utilization, 4))}}


# ---------------------------------------------------------------------------
# the collector / merger
# ---------------------------------------------------------------------------


class ReplicaTelemetry:
    """Latest (and previous) scraped export of one replica, plus the
    derived recent-window rates the outlier detector reads."""

    __slots__ = ("idx", "export", "prev", "t", "prev_t", "stale")

    def __init__(self, idx: int):
        self.idx = idx
        self.export: Optional[Dict] = None
        self.prev: Optional[Dict] = None
        self.t: float = 0.0
        self.prev_t: float = 0.0
        self.stale = True

    def ingest(self, export: Dict, now: float) -> None:
        self.prev, self.prev_t = self.export, self.t
        self.export, self.t = export, now
        self.stale = False

    def _hist_delta(self, name: str) -> Optional[float]:
        """Mean of ``name`` over the most recent scrape interval
        (sum/total deltas between consecutive exports); falls back to
        the lifetime mean ONLY on the first scrape. A quiescent
        interval (no new observations) returns None — an idle replica
        must not keep presenting its stale lifetime numbers to the
        outlier detector (a replica slow an hour ago but idle now is
        not a current outlier)."""
        if self.export is None:
            return None
        cur = (self.export.get("histograms") or {}).get(name)
        if not cur:
            return None
        prev = ((self.prev.get("histograms") or {}).get(name)
                if self.prev else None)
        if prev is None:
            return cur["sum"] / cur["total"] if cur["total"] else None
        if cur["total"] > prev["total"]:
            return ((cur["sum"] - prev["sum"])
                    / (cur["total"] - prev["total"]))
        return None

    def _counter_rate(self, name: str, per: str = "s"
                      ) -> Optional[float]:
        """Delta of counter ``name`` per second (or per engine step
        with ``per="step"``) over the most recent scrape interval."""
        if self.export is None or self.prev is None:
            return None
        c1 = (self.export.get("counters") or {}).get(name)
        c0 = (self.prev.get("counters") or {}).get(name)
        if c1 is None or c0 is None:
            return None
        if per == "step":
            s1 = (self.export.get("gauges") or {}).get("engine_steps")
            s0 = (self.prev.get("gauges") or {}).get("engine_steps")
            if not s1 or s0 is None or s1 <= s0:
                return None
            return (c1 - c0) / (s1 - s0)
        dt = self.t - self.prev_t
        return (c1 - c0) / dt if dt > 0 else None

    def signals(self) -> Dict[str, Optional[float]]:
        """The outlier detector's per-replica inputs."""
        return {"step_ms": self._hist_delta("step_ms"),
                "tpot_ms": self._hist_delta("tpot_ms"),
                "error_rate":
                    self._counter_rate("engine_errors_total",
                                       per="step")}


class FleetMetrics:
    """Aggregates replica exports into the fleet surface.

    ``ingest(idx, export)`` is called by the supervisor's monitor
    loop after each healthy probe+scrape; ``mark_stale(idx)`` when a
    replica dies or a scrape fails (its last export is KEPT for
    postmortems but excluded from fleet rollups). All read surfaces
    (``fleet_snapshot``, ``prometheus_text``) may run on router
    connection threads, hence the lock."""

    def __init__(self, outlier_z: float = 3.5,
                 stale_after_s: float = 10.0,
                 pressure: Optional[PressureMonitor] = None,
                 pressure_interval_s: float = 1.0):
        self.outlier_z = float(outlier_z)
        self.stale_after_s = float(stale_after_s)
        self.pressure = pressure or PressureMonitor()
        # minimum wall time between pressure-hysteresis advances: a
        # scrape cycle ingests N replicas back-to-back (N generation
        # bumps), and router picks may read between them — without
        # this gate one bursty cycle could step the streak N times
        # and flip the verdict in a single cycle. One advance per
        # interval means hysteresis=K needs >= K*interval seconds of
        # SUSTAINED signal, which is the contract.
        self.pressure_interval_s = float(pressure_interval_s)
        self._replicas: Dict[int, ReplicaTelemetry] = {}
        self._lock = threading.Lock()
        self.scrapes_total = 0
        self.scrape_failures_total = 0
        self.outlier_flags_total = 0
        self._flagged: Dict[int, Dict] = {}
        # evaluation is GENERATION-GATED: _gen bumps on every ingest/
        # stale transition, and outlier flags + the pressure verdict
        # only advance when the generation changed since the last
        # evaluation. Read-side polls (fleet_stats, exposition
        # scrapes, router picks) therefore can't flap the hysteretic
        # verdict by polling fast, and the flags stay current even
        # with NO poller-independent driver — the first reader after
        # a scrape cycle pays the (small) evaluation.
        self._gen = 0
        self._eval_gen = -1
        self._eval_t = 0.0
        self._pressure_t: Optional[float] = None
        self._eval_fresh_ids: tuple = ()
        self._last_eval: Optional[Dict] = None
        # verdict→action latch (r21): the autoscaler consumes each
        # pressure evaluation at most once — this remembers the
        # _pressure_t it last handed out
        self._consumed_pressure_t: Optional[float] = None

    # -- ingestion (monitor loop) ------------------------------------------

    def ingest(self, idx: int, export: Dict) -> None:
        now = time.monotonic()
        with self._lock:
            rt = self._replicas.setdefault(idx, ReplicaTelemetry(idx))
            rt.ingest(export, now)
            self.scrapes_total += 1
            self._gen += 1

    def mark_stale(self, idx: int) -> None:
        """A replica died / failed its scrape: keep its last export
        for postmortems but drop it from fleet rollups until it
        reports again (no poisoned fleet totals)."""
        with self._lock:
            rt = self._replicas.setdefault(idx, ReplicaTelemetry(idx))
            if not rt.stale:
                rt.stale = True
                self.scrape_failures_total += 1
                self._gen += 1

    def _fresh(self, now: float) -> List[ReplicaTelemetry]:
        return [rt for rt in self._replicas.values()
                if not rt.stale and rt.export is not None
                and now - rt.t <= self.stale_after_s]

    # -- evaluation (generation-gated; lock held) --------------------------

    def _evaluate_locked(self, now: float) -> Dict:
        """Recompute the merged fleet view, outlier flags, and the
        pressure verdict. Must be called with the lock held; the
        returned dict is replaced wholesale, never mutated, so
        callers may read it after releasing the lock.

        Two-level gating: the MERGE/flag recompute is cached for up
        to 1 s when no new telemetry arrived (poll storms stay
        cheap), but never longer — freshness depends on wall time, so
        replicas aging past ``stale_after_s`` must fall out of the
        rollup even when nothing bumps the generation (e.g. a wedged
        monitor thread). The PRESSURE verdict advances only on NEW
        INFORMATION — a generation bump or a change in the fresh
        set — and at most once per ``pressure_interval_s``, so
        neither read-side polls nor the N per-replica ingests of one
        scrape cycle can flap the hysteresis."""
        gen_changed = self._eval_gen != self._gen
        if (not gen_changed and self._last_eval is not None
                and now - self._eval_t < 1.0):
            return self._last_eval
        fresh = self._fresh(now)
        fresh_ids = tuple(sorted(rt.idx for rt in fresh))
        merged = _merge_fresh_exports(fresh)
        flagged = self._detect_outliers(fresh)
        for idx in flagged:
            if idx not in self._flagged:
                self.outlier_flags_total += 1
        self._flagged = flagged
        att = attainment_from_export(merged["slo"])
        new_info = (gen_changed or self._last_eval is None
                    or fresh_ids != self._eval_fresh_ids)
        if not fresh:
            # a telemetry BLACKOUT is not an idle fleet: with zero
            # fresh replicas there is no evidence for any scaling
            # move — hold the last published verdict, mark the raw
            # input as no_data, and leave the hysteresis state
            # untouched
            pressure = {"verdict": self.pressure.verdict,
                        "raw": "no_data", "streak": 0,
                        "hysteresis": self.pressure.hysteresis,
                        "inputs": None}
        elif new_info and (
                self._pressure_t is None
                or now - self._pressure_t >= self.pressure_interval_s):
            gauges = merged["gauges"]
            n_fresh = len(fresh)
            slots = gauges.get("num_slots", 0.0)
            inflight = gauges.get("inflight_slots", 0.0)
            # memory input (r18): fleet page-pool utilization from the
            # scraped occupancy gauges (a ratio of sums across fresh
            # replicas; per-replica detail lives in fleet_capacity).
            # UNRECLAIMABLE pages when the replica exports them (raw
            # used minus refcount-0 cache pages — a warm inclusive
            # cache fills the pool by design and must not read as
            # exhaustion); pages_used is the pre-refinement fallback.
            pool = gauges.get("num_pages", 0.0)
            used = gauges.get("pages_unreclaimable")
            if used is None:
                used = gauges.get("pages_used")
            mem_util = ((used / pool)
                        if pool and used is not None else None)
            slo = merged["slo"]
            pressure = self.pressure.evaluate(
                att.get("all")
                if (slo.get("ttft_ms") is not None
                    or slo.get("tpot_ms") is not None) else None,
                gauges.get("queued_requests", 0.0) / n_fresh,
                gauges.get("prefill_debt_tokens", 0.0) / n_fresh,
                (inflight / slots) if slots else None,
                mem_utilization=mem_util)
            self._pressure_t = now
        elif self._last_eval is not None:
            pressure = self._last_eval["pressure"]
        else:
            pressure = {"verdict": self.pressure.verdict,
                        "raw": "no_data", "streak": 0,
                        "hysteresis": self.pressure.hysteresis,
                        "inputs": None}
        self._last_eval = {"fresh": fresh, "merged": merged,
                           "flagged": flagged, "attainment": att,
                           "pressure": pressure}
        self._eval_gen = self._gen
        self._eval_t = now
        self._eval_fresh_ids = fresh_ids
        return self._last_eval

    # -- outlier detection -------------------------------------------------

    def _detect_outliers(self, fresh: List[ReplicaTelemetry]
                         ) -> Dict[int, Dict]:
        """Robust z-score per signal over the fresh replicas; a
        replica flags when any signal's score exceeds ``outlier_z``
        in the SLOW/ERRORful direction (fast replicas are not
        outliers worth avoiding)."""
        flagged: Dict[int, Dict] = {}
        for sig in ("step_ms", "tpot_ms", "error_rate"):
            vals = {rt.idx: v for rt in fresh
                    for v in [rt.signals()[sig]] if v is not None}
            for idx, z in robust_zscores(vals).items():
                if z > self.outlier_z:
                    flagged.setdefault(idx, {})[sig] = {
                        "z": round(z, 2), "value": round(vals[idx], 4)}
        return flagged

    def outliers(self) -> Dict[int, Dict]:
        """Currently-flagged replicas — evaluated lazily against the
        latest scrape generation, so the router's deprioritization
        path stays current even when nothing polls fleet_stats."""
        with self._lock:
            return dict(self._evaluate_locked(
                time.monotonic())["flagged"])

    def consume_pressure(self) -> Optional[Dict]:
        """Verdict→action latch (r21): the pressure dict when a NEW
        pressure evaluation ran since the last consume, else None.
        The autoscaler drives actions through this, so each fresh
        evaluation can trigger at most ONE action — replayed reads
        (poll storms, a fast actuator tick) and telemetry blackouts
        (verdict held, nothing evaluated) return None and cause
        nothing. ``fleet_snapshot``/``outliers`` reads never consume:
        observation stays side-effect-free."""
        with self._lock:
            ev = self._evaluate_locked(time.monotonic())
            if self._pressure_t is None or \
                    self._pressure_t == self._consumed_pressure_t:
                return None
            self._consumed_pressure_t = self._pressure_t
            return dict(ev["pressure"])

    # -- fleet surfaces ----------------------------------------------------

    def fleet_snapshot(self) -> Dict:
        """The telemetry half of the ``fleet_stats`` payload: merged
        counters/histograms/SLO, pressure verdict, outlier flags, and
        per-replica telemetry state (staleness, signals, counters).
        The supervision half — probe-failure taxonomy, restarts,
        backoff gates — is joined in by ``Supervisor.fleet_stats``,
        which owns that state."""
        now = time.monotonic()
        with self._lock:
            ev = self._evaluate_locked(now)
            all_rt = dict(self._replicas)
            scrapes = self.scrapes_total
            scrape_failures = self.scrape_failures_total
            flags_total = self.outlier_flags_total
        fresh = ev["fresh"]
        flagged = ev["flagged"]
        counters = ev["merged"]["counters"]
        gauges = ev["merged"]["gauges"]
        hists = ev["merged"]["histograms"]
        slo = ev["merged"]["slo"]
        att = ev["attainment"]
        pressure = ev["pressure"]

        per_replica = {}
        for idx, rt in sorted(all_rt.items()):
            sig = rt.signals()
            # string keys: this dict crosses a JSON socket (the
            # router's fleet_stats op) where int keys would silently
            # become strings anyway — one spelling everywhere
            per_replica[str(idx)] = {
                "stale": rt.stale or now - rt.t > self.stale_after_s,
                "age_s": (round(now - rt.t, 3) if rt.export is not None
                          else None),
                "signals": {k: (None if v is None else round(v, 4))
                            for k, v in sig.items()},
                "outlier": flagged.get(idx),
                "counters": dict(rt.export.get("counters") or {})
                if rt.export else {},
            }
        return {"replicas_fresh": len(fresh),
                "replicas_known": len(all_rt),
                "counters": counters,
                "gauges": {k: round(v, 4) for k, v in gauges.items()},
                "histograms": {k: (export_snapshot(v)
                                   if "error" not in v else v)
                               for k, v in hists.items()},
                "histogram_exports": hists,
                "slo": {"targets": {"ttft_ms": slo.get("ttft_ms"),
                                    "tpot_ms": slo.get("tpot_ms")},
                        "window_s": slo.get("window_s"),
                        "classes": slo.get("classes"),
                        "attainment": att},
                "pressure": pressure,
                "outliers": {str(k): v for k, v in flagged.items()},
                "collector": {"scrapes_total": scrapes,
                              "scrape_failures_total": scrape_failures,
                              "outlier_flags_total": flags_total},
                "per_replica": per_replica}

    def prometheus_text(self, prefix: str = "serving") -> str:
        """Fleet text exposition: per-replica series keep their
        replica-local family names with a ``replica`` label; fleet
        rollups live under DISTINCT ``fleet_``-prefixed families (an
        unlabeled rollup inside a labeled family would collide — the
        registry-audit lesson, fleet edition)."""
        now = time.monotonic()
        with self._lock:
            ev = self._evaluate_locked(now)
        fresh = ev["fresh"]
        lines: List[str] = []
        # per-replica series, replica-labeled, FAMILY-GROUPED (one
        # TYPE line per family, samples contiguous across replicas —
        # the text-format contract strict scrapers enforce)
        lines.extend(prometheus_multi_export_lines(
            [({"replica": str(rt.idx)}, rt.export)
             for rt in sorted(fresh, key=lambda r: r.idx)],
            prefix=prefix))
        # fleet rollups, unlabeled, own families — the SAME merged
        # view fleet_snapshot serves (one merge path, no drift);
        # mismatched-ladder histograms carry an "error" entry and are
        # skipped here (they still surface in fleet_stats JSON)
        if fresh:
            merged = ev["merged"]
            lines.extend(prometheus_export_lines(
                {"counters": merged["counters"],
                 "gauges": merged["gauges"],
                 "histograms": {k: v for k, v in
                                merged["histograms"].items()
                                if "error" not in v}},
                prefix="fleet", labels=None))
            att = ev["attainment"]
            slo = merged["slo"]
            if slo.get("ttft_ms") is not None \
                    or slo.get("tpot_ms") is not None:
                lines.append("# TYPE fleet_slo_attainment gauge")
                for cls in sorted(att):
                    if att[cls] is not None:
                        lines.append(
                            f'fleet_slo_attainment{{class="{cls}"}} '
                            f"{att[cls]:g}")
        lines.append("# TYPE fleet_replicas_fresh gauge")
        lines.append(f"fleet_replicas_fresh {len(fresh)}")
        return "\n".join(lines) + "\n"


def _label_str(labels: Optional[Dict[str, str]]) -> str:
    """Validated ``k="v"[,...]`` label body (empty string = no
    labels). Label values must be bare — no quotes, backslashes or
    newlines; malformed ones raise rather than emit an unparseable
    page."""
    if not labels:
        return ""
    for k, v in labels.items():
        if any(c in str(v) for c in '"\\\n'):
            raise ValueError(f"malformed label value {v!r}")
    return ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))


def prometheus_multi_export_lines(
        pairs: List, prefix: str = "serving") -> List[str]:
    """Exposition lines for N labeled exports, FAMILY-GROUPED: each
    family declares its ``# TYPE`` exactly once and all its samples
    (one per labeled export) are contiguous — the text-format
    contract strict scrapers enforce ("all lines for a given metric
    must be provided as one single group"). ``pairs`` is a list of
    ``(labels_or_None, export_dict)``."""
    pairs = [( _label_str(labels), e) for labels, e in pairs if e]
    out: List[str] = []
    hist_names = sorted({h for _lab, e in pairs
                         for h in (e.get("histograms") or {})})
    for hname in hist_names:
        name = f"{prefix}_{hname}".replace(".", "_")
        lines: List[str] = []
        for lab, e in pairs:
            h = (e.get("histograms") or {}).get(hname)
            if not h or "counts" not in h:
                continue
            acc = 0
            sep = "," if lab else ""
            for le, c in zip(h["buckets"], h["counts"]):
                acc += c
                lines.append(
                    f'{name}_bucket{{{lab}{sep}le="{le:g}"}} {acc}')
            acc += h["counts"][-1]
            lines.append(f'{name}_bucket{{{lab}{sep}le="+Inf"}} {acc}')
            brace = f"{{{lab}}}" if lab else ""
            lines.append(f'{name}_sum{brace} {h["sum"]:g}')
            lines.append(f'{name}_count{brace} {h["total"]}')
        if lines:
            out.append(f"# TYPE {name} histogram")
            out.extend(lines)
    gauge_names = sorted({g for _lab, e in pairs
                          for g, v in (e.get("gauges") or {}).items()
                          if isinstance(v, (int, float))})
    for gname in gauge_names:
        name = f"{prefix}_{gname}".replace(".", "_")
        lines = []
        for lab, e in pairs:
            v = (e.get("gauges") or {}).get(gname)
            if not isinstance(v, (int, float)):
                continue
            brace = f"{{{lab}}}" if lab else ""
            lines.append(f"{name}{brace} {v:g}")
        if lines:
            out.append(f"# TYPE {name} gauge")
            out.extend(lines)
    counter_names = sorted({c for _lab, e in pairs
                            for c in (e.get("counters") or {})})
    for cname in counter_names:
        name = f"{prefix}_{cname}".replace(".", "_")
        lines = []
        for lab, e in pairs:
            v = (e.get("counters") or {}).get(cname)
            if v is None:
                continue
            brace = f"{{{lab}}}" if lab else ""
            lines.append(f"{name}{brace} {v}")
        if lines:
            out.append(f"# TYPE {name} counter")
            out.extend(lines)
    return out


def prometheus_export_lines(export: Dict, prefix: str = "serving",
                            labels: Optional[Dict[str, str]] = None
                            ) -> List[str]:
    """Exposition lines for one ``ServingMetrics.export()``-shaped
    dict (see ``prometheus_multi_export_lines`` for the N-replica,
    family-grouped form)."""
    return prometheus_multi_export_lines([(labels, export)],
                                         prefix=prefix)


# ---------------------------------------------------------------------------
# crash flight recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Black-box bundle writer with a byte-budgeted retention ring.

    ``record(reason, collect)`` assembles a bundle from the
    ``collect()`` callback (the server passes a closure over its
    engine/tracer/metrics), writes it ATOMICALLY (tmp + rename — a
    crash mid-write never leaves a torn bundle for the inspector),
    then prunes OLDEST-FIRST until the directory is back under
    ``budget_bytes`` (the newest bundle always survives, even if it
    alone exceeds the budget: the most recent crash is the one the
    postmortem needs). Bundle writes must never take the serving path
    down — failures are counted, not raised. ``min_interval_s``
    rate-limits per-reason recording so a stall storm can't turn the
    engine thread into a JSON serializer."""

    def __init__(self, flight_dir: str,
                 budget_bytes: int = 64 << 20,
                 min_interval_s: float = 1.0):
        self.flight_dir = flight_dir
        self.budget_bytes = int(budget_bytes)
        self.min_interval_s = float(min_interval_s)
        os.makedirs(flight_dir, exist_ok=True)
        self.recorded_total = 0
        self.record_failures_total = 0
        self.pruned_total = 0
        self._seq = 0
        self._last_t: Dict[str, float] = {}
        self._lock = threading.Lock()

    def record(self, reason: str,
               collect: Callable[[], Dict]) -> Optional[str]:
        """Write one bundle; returns its path (None when rate-limited
        or failed)."""
        now = time.monotonic()
        with self._lock:
            last = self._last_t.get(reason)
            if last is not None and now - last < self.min_interval_s:
                return None
            self._last_t[reason] = now
            self._seq += 1
            seq = self._seq
        try:
            bundle = collect()
            bundle.setdefault("v", 1)
            bundle["reason"] = reason
            bundle["t_unix"] = time.time()
            bundle["pid"] = os.getpid()
            name = (f"flight-{int(bundle['t_unix'] * 1e3):013d}"
                    f"-{seq:04d}-{reason}.json")
            path = os.path.join(self.flight_dir, name)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(bundle, f, default=_json_default)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self.recorded_total += 1
            self._prune(keep=name)
            return path
        except Exception:
            self.record_failures_total += 1
            return None

    def bundles(self) -> List[str]:
        """Committed bundle paths, oldest first (name-sorted: names
        embed ms timestamps + a sequence number)."""
        try:
            names = sorted(n for n in os.listdir(self.flight_dir)
                           if n.startswith("flight-")
                           and n.endswith(".json"))
        except OSError:
            return []
        return [os.path.join(self.flight_dir, n) for n in names]

    def total_bytes(self) -> int:
        return sum(os.path.getsize(p) for p in self.bundles()
                   if os.path.exists(p))

    def _prune(self, keep: str) -> None:
        paths = self.bundles()
        sizes = {p: os.path.getsize(p) for p in paths
                 if os.path.exists(p)}
        total = sum(sizes.values())
        for p in paths:
            if total <= self.budget_bytes:
                break
            if os.path.basename(p) == keep:
                continue  # the newest bundle always survives
            try:
                os.unlink(p)
                total -= sizes.get(p, 0)
                self.pruned_total += 1
            except OSError:
                pass


def _json_default(obj):
    """Bundles carry whatever the engine snapshot holds — numpy
    scalars/arrays and the odd object; degrade to something readable
    rather than failing the write."""
    for attr in ("item", "tolist"):
        fn = getattr(obj, attr, None)
        if callable(fn):
            try:
                return fn()
            except Exception:
                break
    return repr(obj)
