"""Refcounted cross-request KV prefix cache over the page allocator.

The page-granular KV layout (*Ragged Paged Attention*, PAPERS.md) makes
prompt prefixes shareable for free: a FULL page of KV is an immutable
function of (model weights, the token block it covers, and every token
before it). This module keys such pages by a rolling hash of their
token block chained through the prefix, so two requests whose prompts
share a prefix share the physical pages — the shared-system-prompt
serving workload then skips that prefill compute entirely (the engine
prefills only the suffix via models/gpt.py ``prefill_chained``).

Invariants the tests pin (tests/test_serving.py):

- Only FULL pages strictly inside the prompt are ever shared; the
  shareable block count for a prompt of length L is ``(L - 1) //
  page_size``, so at least one suffix token always remains to prefill
  (its logits produce the first generated token, and a fully-cached
  prompt would otherwise have no forward pass to produce them).
- Shared pages are IMMUTABLE: divergence past the shared prefix is a
  write into fresh private pages (the copy-on-write of this design —
  the diverging request never touches the shared page, it writes its
  own), and decode appends always land at positions past the prompt,
  hence past every shared page.
- Entries are refcounted (one ref per active request per chain entry,
  plus one per child entry); LRU eviction considers ONLY entries with
  refcount 0 and no children, so a chain is torn down leaf-first and
  never under an active request.
- Ownership is explicit in the `PageAllocator` books: cached pages
  belong to ``("prefix", key)`` owners, so ``check_no_leak`` still
  audits every page — `clear()` (engine close) returns everything and
  the allocator must come out whole.

Reference analog: no fluid-era equivalent (the inference engine caches
whole programs, not KV); this is the serving-layer capability the
paged pool was built to unlock.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, Hashable, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["PrefixCache"]


def _block_hash(parent: Optional[bytes], block: np.ndarray) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    if parent is not None:
        h.update(parent)
    h.update(np.ascontiguousarray(block, np.int32).tobytes())
    return h.digest()


@dataclasses.dataclass
class _Entry:
    key: bytes
    parent: Optional[bytes]
    page: int
    tokens: np.ndarray            # the block's tokens (collision guard)
    refcount: int = 0             # active requests holding this entry
    children: int = 0             # child entries chaining off this one
    last_used: int = 0            # LRU tick


class PrefixCache:
    """Host-side refcounted prefix-page cache.

    Single-threaded by design: every method runs on the engine thread
    (the server serializes engine access), matching the allocator's
    model. ``page_size`` must equal the engine's."""

    def __init__(self, page_size: int, max_pages: Optional[int] = None):
        self.page_size = int(page_size)
        # optional soft cap on cached pages; None = bounded only by
        # pool pressure (evict_until)
        self.max_pages = max_pages
        self._entries: Dict[bytes, _Entry] = {}
        self._tick = 0
        # lifetime counters (serving/metrics.py scrapes these through
        # the engine's RequestStats; kept here too for direct audits)
        self.hit_pages = 0
        self.miss_pages = 0
        self.inserted_pages = 0
        self.evicted_pages = 0

    # -- keys --------------------------------------------------------------

    def _shareable_blocks(self, prompt: np.ndarray) -> int:
        # full pages strictly before the last prompt token: guarantees
        # a non-empty suffix prefill (see module docstring)
        return max(0, (len(prompt) - 1) // self.page_size)

    def _chain_keys(self, prompt: np.ndarray
                    ) -> List[Tuple[bytes, Optional[bytes], np.ndarray]]:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        out: List[Tuple[bytes, Optional[bytes], np.ndarray]] = []
        parent: Optional[bytes] = None
        for i in range(self._shareable_blocks(prompt)):
            block = prompt[i * self.page_size:(i + 1) * self.page_size]
            key = _block_hash(parent, block)
            out.append((key, parent, block))
            parent = key
        return out

    # -- lookup / refcounts ------------------------------------------------

    def match(self, prompt, memo=None
              ) -> Tuple[Tuple[bytes, ...], List[int]]:
        """Longest cached prefix for ``prompt``: (chain keys, pages).
        Pure — no refcounts move (admission calls ``acquire`` once it
        commits; ``_fits`` probes freely). ``memo`` (typically the
        DecodeRequest) caches the chain hashes across calls — the
        prompt is immutable, and per-step admission probes must cost
        dict lookups, not O(prompt) re-hashing."""
        chain = getattr(memo, "_pfx_chain", None) if memo is not None \
            else None
        if chain is None:
            chain = self._chain_keys(prompt)
            if memo is not None:
                memo._pfx_chain = chain
        keys: List[bytes] = []
        pages: List[int] = []
        for key, _parent, block in chain:
            ent = self._entries.get(key)
            if ent is None or not np.array_equal(ent.tokens, block):
                break  # miss (or hash collision — treated as a miss)
            keys.append(key)
            pages.append(ent.page)
        return tuple(keys), pages

    def acquire(self, keys: Sequence[bytes]) -> None:
        """Pin a matched chain for an admitting request (one ref per
        entry). Hit/miss stats are counted once, at ``insert`` (an
        admission that later unwinds releases without skewing them)."""
        self._tick += 1
        for k in keys:
            ent = self._entries[k]
            ent.refcount += 1
            ent.last_used = self._tick

    def release(self, keys: Sequence[bytes]) -> None:
        for k in keys:
            ent = self._entries.get(k)
            if ent is None:
                continue  # entry force-cleared (close() teardown)
            ent.refcount -= 1
            if ent.refcount < 0:
                raise RuntimeError(
                    f"prefix-cache refcount underflow on {k.hex()}")

    # -- insertion ---------------------------------------------------------

    def insert(self, prompt, row: np.ndarray, allocator, owner: Hashable,
               page_size: int, matched_keys: Sequence[bytes]
               ) -> Tuple[bytes, ...]:
        """Adopt the freshly-prefilled full prompt pages of ``row``
        into the cache (ownership transfer ``owner`` → cache) and
        return the request's full chain keys (matched + new), each
        holding one reference for the request.

        ``row`` is the slot's page-table row: entry i is the physical
        page of token block i, so the new blocks' pages are read
        straight out of it."""
        if page_size != self.page_size:
            raise ValueError(
                f"engine page_size {page_size} != cache page_size "
                f"{self.page_size}")
        chain = self._chain_keys(prompt)
        keys: List[bytes] = list(matched_keys)
        self.hit_pages += len(matched_keys)
        self.miss_pages += max(0, len(chain) - len(matched_keys))
        for i in range(len(matched_keys), len(chain)):
            key, parent, block = chain[i]
            ent = self._entries.get(key)
            if ent is not None and np.array_equal(ent.tokens, block):
                # already cached (defensive: cannot happen on the
                # single-threaded admission path, where match() ran
                # moments ago) — take a reference, keep our private
                # copy with the request (freed when it finishes)
                ent.refcount += 1
                ent.last_used = self._tick
                keys.append(key)
                continue
            if ent is not None:
                break  # hash collision with different tokens: stop
            if self.max_pages is not None and \
                    self.total_pages() >= self.max_pages and \
                    not self._evict_one(allocator):
                break  # soft cap reached and nothing evictable
            page = int(row[i])
            allocator.transfer(owner, ("prefix", key), [page])
            self._tick += 1
            self._entries[key] = _Entry(key, parent, page,
                                        np.array(block, np.int32),
                                        refcount=1, last_used=self._tick)
            if parent is not None:
                self._entries[parent].children += 1
            self.inserted_pages += 1
            keys.append(key)
        return tuple(keys)

    # -- eviction ----------------------------------------------------------

    def _evictable(self) -> List[_Entry]:
        return [e for e in self._entries.values()
                if e.refcount == 0 and e.children == 0]

    def evictable_pages(self, excluding: Sequence[bytes] = ()) -> int:
        """Pages reclaimable RIGHT NOW plus transitively (a refcount-0
        parent becomes evictable once its refcount-0 leaves go): every
        entry not pinned by some active request at or below it.
        ``excluding`` marks entries the CALLER is about to pin (its own
        prefix match) — counting those as evictable would make
        admission-fit checks optimistic about pages that the admission
        itself takes off the table."""
        pinned: set = set()
        for start in list(excluding):
            k: Optional[bytes] = start
            while k is not None and k not in pinned and \
                    k in self._entries:
                pinned.add(k)
                k = self._entries[k].parent
        for e in self._entries.values():
            if e.refcount > 0:
                k = e.key
                while k is not None and k not in pinned:
                    pinned.add(k)
                    k = self._entries[k].parent
        return len(self._entries) - len(pinned)

    def _evict_one(self, allocator) -> bool:
        cands = self._evictable()
        if not cands:
            return False
        victim = min(cands, key=lambda e: e.last_used)
        allocator.free(("prefix", victim.key))
        if victim.parent is not None:
            self._entries[victim.parent].children -= 1
        del self._entries[victim.key]
        self.evicted_pages += 1
        return True

    def evict_until(self, allocator, need_free: int) -> bool:
        """LRU-evict refcount-0 leaves until the allocator has
        ``need_free`` free pages (True) or nothing evictable remains
        (False)."""
        while allocator.free_count < need_free:
            if not self._evict_one(allocator):
                return False
        return True

    def clear(self, allocator) -> None:
        """Return every cached page to the allocator (engine close()).
        Active references must already be gone — a nonzero refcount
        here is a lifecycle bug, not cache pressure."""
        busy = [e for e in self._entries.values() if e.refcount > 0]
        if busy:
            raise RuntimeError(
                f"prefix-cache clear with {len(busy)} entries still "
                f"referenced (refcounts "
                f"{[e.refcount for e in busy[:8]]}) — release requests "
                f"before close()")
        for ent in self._entries.values():
            allocator.free(("prefix", ent.key))
        self.evicted_pages += len(self._entries)
        self._entries.clear()

    # -- audits ------------------------------------------------------------

    def total_pages(self) -> int:
        return len(self._entries)

    def hit_rate(self) -> Optional[float]:
        seen = self.hit_pages + self.miss_pages
        return self.hit_pages / seen if seen else None

    def check_consistent(self, allocator) -> None:
        """Drained-engine audit: every page the allocator still sees as
        owned must be a cache page, and the books must balance —
        free + cached == pool size. The with-cache analog of
        ``PageAllocator.check_no_leak``."""
        owners = allocator.owners()
        cache_owned = 0
        for owner, pages in owners.items():
            if not (isinstance(owner, tuple) and len(owner) == 2
                    and owner[0] == "prefix"):
                raise RuntimeError(
                    f"page leak past drain: owner {owner!r} still holds "
                    f"{list(pages)}")
            ent = self._entries.get(owner[1])
            if ent is None or tuple(pages) != (ent.page,):
                raise RuntimeError(
                    f"prefix-cache books diverge from allocator for "
                    f"owner {owner!r}: allocator={list(pages)}, "
                    f"entry={ent}")
            cache_owned += len(pages)
        if allocator.free_count + cache_owned != allocator.num_pages:
            raise RuntimeError(
                f"page accounting broken: {allocator.free_count} free + "
                f"{cache_owned} cached != pool {allocator.num_pages}")
