"""Refcounted cross-request KV prefix cache over the page allocator.

The page-granular KV layout (*Ragged Paged Attention*, PAPERS.md) makes
prompt prefixes shareable for free: a FULL page of KV is an immutable
function of (model weights, the token block it covers, and every token
before it). This module keys such pages by a rolling hash of their
token block chained through the prefix, so two requests whose prompts
share a prefix share the physical pages — the shared-system-prompt
serving workload then skips that prefill compute entirely (the engine
prefills only the suffix via models/gpt.py ``prefill_chained``).

Invariants the tests pin (tests/test_serving.py):

- Only FULL pages strictly inside the prompt are ever shared; the
  shareable block count for a prompt of length L is ``(L - 1) //
  page_size``, so at least one suffix token always remains to prefill
  (its logits produce the first generated token, and a fully-cached
  prompt would otherwise have no forward pass to produce them).
- Shared pages are IMMUTABLE: divergence past the shared prefix is a
  write into fresh private pages (the copy-on-write of this design —
  the diverging request never touches the shared page, it writes its
  own), and decode appends always land at positions past the prompt,
  hence past every shared page.
- Entries are refcounted (one ref per active request per chain entry,
  plus one per child entry); LRU eviction considers ONLY entries with
  refcount 0 and no children, so a chain is torn down leaf-first and
  never under an active request.
- Ownership is explicit in the `PageAllocator` books: cached pages
  belong to ``("prefix", key)`` owners, so ``check_no_leak`` still
  audits every page — `clear()` (engine close) returns everything and
  the allocator must come out whole.

Hierarchical spill tiers (r15): eviction is no longer oblivion. With
``spill_bytes`` (host RAM) and/or ``spill_dir`` (disk) configured, a
refcount-0 FULL page that ``_evict_one`` would free is first copied
device→host as an immutable content blob keyed by the SAME chained
blake2b block key — the key already proves the content, so a later
``match()`` that misses device pages can restore the blob into freshly
allocated pages (one device_put + page-table splice, models/gpt.py
``paged_page_splice``) instead of re-running the prefix's prefill. A
tier miss mid-chain just shortens the restored prefix: the remaining
suffix rides the existing chained-prefill machinery, so restore-hit,
partial-hit and miss paths all produce bit-identical greedy output.
Each tier is byte-budgeted LRU; the host tier demotes into the disk
tier, the last tier drops. Blobs carry a crc32 — a corrupt blob is a
typed, counted fallback to chained prefill, never wrong tokens
(``cache.spill`` fault site, distributed/fault_inject.py).

KV byte substrate (r23): the blob is now a CODEC boundary, not just a
container. ``pack_page_blob`` gains per-format encodings — ``raw``
(byte-for-byte the r22 layout), ``int8`` and ``int4`` — used by the
spill tiers, ``fetch_pages`` exports and the drain handoff, so host
RAM, disk and the wire move 2–4× fewer bytes per page. Blobs stay
self-describing (the meta header names the POOL layout and the
format), so ``unpack_page_blob`` always decodes back to exactly the
pool's layout and the splice path is format-oblivious. An engine
already on int8 pages packs its int8 bytes losslessly (bit-identical
round trip); a float engine opting into ``int8``/``int4`` gets the
pinned ``deq = q * s / qmax`` decode (quantization/quant.py — the
same convention the attention kernel applies in-VMEM) with the
encode error accumulated in ``codec_stats``, never silent. Identical
FULL pages arriving from unrelated requests dedup against the
resident entry (``dedup=True``): the chained blake2b key plus a
token-block equality check prove content, the private duplicate page
returns to the free list, and the shared page moves to a
``("dedup", key)`` owner so the allocator books say which pages are
cross-request shared (``occupancy()``'s ``dedup`` class).

Reference analog: no fluid-era equivalent (the inference engine caches
whole programs, not KV); this is the serving-layer capability the
paged pool was built to unlock.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import os
import struct
import time
import zlib
from collections import OrderedDict
from typing import (Any, Callable, Dict, Hashable, List, Optional,
                    Sequence, Tuple)

import numpy as np

__all__ = ["PrefixCache", "HostSpillTier", "DiskSpillTier",
           "SpillCorrupt", "pack_page_blob", "unpack_page_blob",
           "blob_logical_bytes", "BLOB_FORMATS"]


def _block_hash(parent: Optional[bytes], block: np.ndarray,
                generation: int = 0) -> bytes:
    """Chained block key. ``generation`` (r24 weight hot-swap) salts
    the CHAIN ROOT only: child keys inherit it through the parent
    digest, so one root salt versions every key in the chain. KV bytes
    are a function of the weights that produced them — pages from
    different weight generations must never splice, and distinct root
    salts make cross-generation lookups miss by construction.
    generation=0 (the boot weights) is byte-identical to the pre-r24
    hash, so existing deployments/advertisements are unchanged until
    the first swap."""
    h = hashlib.blake2b(digest_size=16)
    if parent is not None:
        h.update(parent)
    elif generation:
        h.update(b"PTGEN" + struct.pack("<Q", int(generation)))
    h.update(np.ascontiguousarray(block, np.int32).tobytes())
    return h.digest()


# -- spill blobs ------------------------------------------------------------

_BLOB_MAGIC = b"PTKV"

# blob codec formats (r23). "raw" writes the r22 byte layout
# UNCHANGED (4-field meta — the escape hatch an `--blob-format raw`
# deployment pins); "int8"/"int4" write a 5-field meta whose first
# four fields still name the POOL layout, so decode always returns
# exactly what the splice path expects regardless of format.
BLOB_FORMATS = ("raw", "int8", "int4")


class SpillCorrupt(RuntimeError):
    """A spill blob failed its crc32 / structure check. Callers treat
    the blob as a miss (the chained-prefill fallback recomputes the
    page) — corrupt KV must never be spliced into the pool."""


def _frame_blob(meta: bytes, payload: bytes) -> bytes:
    return (_BLOB_MAGIC + struct.pack("<HI", len(meta), len(payload))
            + meta + struct.pack("<I", zlib.crc32(payload)) + payload)


def pack_page_blob(layers: Sequence[Tuple[np.ndarray, np.ndarray,
                                          Optional[np.ndarray],
                                          Optional[np.ndarray]]],
                   fmt: str = "raw",
                   stats: Optional[Dict[str, Any]] = None) -> bytes:
    """Serialize one page's per-layer (k, v, k_scale, v_scale) blocks
    into a self-describing blob: magic + layout header + crc32 over
    the payload + array bytes. Scales are None for fp pages. The
    layout header makes restore independent of caller bookkeeping
    (and lets the audit tests verify byte-equality tier-side).

    ``fmt`` (r23): the transport encoding. ``raw`` is byte-for-byte
    the r22 blob. ``int8`` stores int8 values + float32 per-(token,
    head) scales — a LOSSLESS passthrough when the pool is already
    int8-paged, the pinned ``quantize_kv`` math when it is float.
    ``int4`` stores packed nibbles + float32 scales (quant.py
    ``quantize_kv_int4_np``). Lossy encodes accumulate their error
    into ``stats`` ({"lossy_pages", "max_abs_err"}) — a deployment
    that trades exactness for bytes sees the delta, never silence."""
    if fmt not in BLOB_FORMATS:
        raise ValueError(f"blob format must be one of {BLOB_FORMATS}; "
                         f"got {fmt!r}")
    first_k = np.ascontiguousarray(layers[0][0])
    int8_pool = layers[0][2] is not None
    nl = len(layers)
    shape = first_k.shape                  # [page, H, D]
    dtype = str(first_k.dtype)
    scale_dtype = (str(np.ascontiguousarray(layers[0][2]).dtype)
                   if int8_pool else "")
    if fmt == "int8" and int8_pool:
        fmt = "raw"  # int8 pages ARE the int8 encoding: pure passthrough
    if fmt == "raw":
        payload = bytearray()
        for k, v, ks, vs in layers:
            payload += np.ascontiguousarray(k).tobytes()
            payload += np.ascontiguousarray(v).tobytes()
            if int8_pool:
                payload += np.ascontiguousarray(ks).tobytes()
                payload += np.ascontiguousarray(vs).tobytes()
        meta = (f"{nl};{','.join(map(str, shape))};"
                f"{dtype};{scale_dtype}").encode("ascii")
        return _frame_blob(meta, bytes(payload))
    from ..quantization.quant import (dequantize_kv_np, quantize_kv_np,
                                      quantize_kv_int4_np,
                                      dequantize_kv_int4_np)
    quant = quantize_kv_np if fmt == "int8" else quantize_kv_int4_np
    max_err = 0.0
    payload = bytearray()
    for k, v, ks, vs in layers:
        for block, sc in ((k, ks), (v, vs)):
            x = np.asarray(block, np.float32) if not int8_pool else \
                dequantize_kv_np(block, sc)
            q, s = quant(x)
            if fmt == "int8":
                deq = dequantize_kv_np(q, s)
            else:
                deq = dequantize_kv_int4_np(q, s, x.shape[-1])
            max_err = max(max_err, float(np.max(np.abs(x - deq)))
                          if x.size else 0.0)
            payload += np.ascontiguousarray(q).tobytes()
            payload += np.ascontiguousarray(s).tobytes()
    if stats is not None:
        stats["lossy_pages"] = stats.get("lossy_pages", 0) + 1
        stats["max_abs_err"] = max(stats.get("max_abs_err", 0.0),
                                   max_err)
    meta = (f"{nl};{','.join(map(str, shape))};"
            f"{dtype};{scale_dtype};{fmt}").encode("ascii")
    return _frame_blob(meta, bytes(payload))


def _parse_blob_header(blob: bytes):
    """(meta fields, payload) of a framed blob — crc-checked. Shared
    by :func:`unpack_page_blob` and :func:`blob_logical_bytes`."""
    if blob[:4] != _BLOB_MAGIC:
        raise SpillCorrupt("bad spill-blob magic")
    meta_len, payload_len = struct.unpack("<HI", blob[4:10])
    meta = blob[10:10 + meta_len].decode("ascii")
    off = 10 + meta_len
    crc, = struct.unpack("<I", blob[off:off + 4])
    payload = blob[off + 4:]
    if len(payload) != payload_len:
        raise SpillCorrupt("truncated spill blob")
    if zlib.crc32(payload) != crc:
        raise SpillCorrupt("spill blob crc32 mismatch")
    fields = meta.split(";")
    if len(fields) == 4:
        fields.append("raw")  # r22 blobs: no format field
    if len(fields) != 5 or fields[4] not in BLOB_FORMATS:
        raise SpillCorrupt(f"bad spill-blob meta {meta!r}")
    return fields, payload


def blob_logical_bytes(blob: bytes) -> int:
    """RAW-EQUIVALENT bytes of one blob — the pool-layout bytes its
    page decodes to, independent of transport encoding. The honest
    numerator for spill-tier capacity/hit-rate math after r23: a tier
    holding int4 blobs restores 4× the KV bytes its physical
    occupancy suggests. Falls back to the physical size on a blob it
    cannot parse (the caller is accounting, not restoring — corrupt
    blobs are caught typed at restore/import time)."""
    try:
        (nl_s, shape_s, dtype_s, scale_dtype_s, _fmt), _payload = \
            _parse_blob_header(blob)
        nl = int(nl_s)
        shape = tuple(int(x) for x in shape_s.split(","))
        out = nl * 2 * int(np.prod(shape)) * np.dtype(dtype_s).itemsize
        if scale_dtype_s:
            out += nl * 2 * int(np.prod(shape[:2])) * \
                np.dtype(scale_dtype_s).itemsize
        return out
    except Exception:
        return len(blob)


def unpack_page_blob(blob: bytes
                     ) -> List[Tuple[np.ndarray, np.ndarray,
                                     Optional[np.ndarray],
                                     Optional[np.ndarray]]]:
    """Inverse of :func:`pack_page_blob`; raises :class:`SpillCorrupt`
    on any structural or crc32 mismatch (a torn write, a flipped bit,
    a truncated file — all the same typed fallback). Decodes EVERY
    format back to the pool layout the meta header names: the splice
    path never sees what encoding a blob traveled in. Pinned decode
    math per format (tests/test_kv_substrate.py): raw is a memcpy;
    int8→float is ``q * s / 127``; int4 is nibble-unpack then
    ``q * s / 7``; a coded blob whose pool is int8-paged re-quantizes
    the decoded floats through ``quantize_kv_np`` (the declared,
    deterministic round trip)."""
    try:
        (nl_s, shape_s, dtype_s, scale_dtype_s, fmt), payload = \
            _parse_blob_header(blob)
        nl = int(nl_s)
        shape = tuple(int(x) for x in shape_s.split(","))
        dt = np.dtype(dtype_s)
        int8_pool = bool(scale_dtype_s)
        sdt = np.dtype(scale_dtype_s) if int8_pool else None
        out: List[Tuple[np.ndarray, np.ndarray, Optional[np.ndarray],
                        Optional[np.ndarray]]] = []
        pos = 0
        if fmt == "raw":
            kv_bytes = int(np.prod(shape)) * dt.itemsize
            sc_bytes = (int(np.prod(shape[:2])) * sdt.itemsize
                        if int8_pool else 0)
            for _ in range(nl):
                k = np.frombuffer(payload, dt,
                                  count=int(np.prod(shape)),
                                  offset=pos).reshape(shape)
                pos += kv_bytes
                v = np.frombuffer(payload, dt,
                                  count=int(np.prod(shape)),
                                  offset=pos).reshape(shape)
                pos += kv_bytes
                ks = vs = None
                if int8_pool:
                    n_sc = int(np.prod(shape[:2]))
                    ks = np.frombuffer(payload, sdt, count=n_sc,
                                       offset=pos).reshape(shape[:2])
                    pos += sc_bytes
                    vs = np.frombuffer(payload, sdt, count=n_sc,
                                       offset=pos).reshape(shape[:2])
                    pos += sc_bytes
                out.append((k, v, ks, vs))
            if pos != len(payload):
                raise SpillCorrupt("spill blob payload size mismatch")
            return out
        from ..quantization.quant import (dequantize_kv_np,
                                          dequantize_kv_int4_np,
                                          quantize_kv_np)
        page, heads, head_dim = shape
        if fmt == "int8":
            q_shape, q_dt = shape, np.dtype(np.int8)
        else:
            q_shape = (page, heads, (head_dim + 1) // 2)
            q_dt = np.dtype(np.uint8)
        s_shape, s_dt = (page, heads), np.dtype(np.float32)
        q_bytes = int(np.prod(q_shape)) * q_dt.itemsize
        s_bytes = int(np.prod(s_shape)) * s_dt.itemsize
        for _ in range(nl):
            decoded = []
            for _which in ("k", "v"):
                q = np.frombuffer(payload, q_dt,
                                  count=int(np.prod(q_shape)),
                                  offset=pos).reshape(q_shape)
                pos += q_bytes
                s = np.frombuffer(payload, s_dt,
                                  count=int(np.prod(s_shape)),
                                  offset=pos).reshape(s_shape)
                pos += s_bytes
                if fmt == "int8":
                    x = dequantize_kv_np(q, s)
                else:
                    x = dequantize_kv_int4_np(q, s, head_dim)
                if int8_pool:
                    # back to the int8 pool layout through the SAME
                    # quantizer the append path uses — deterministic,
                    # so the pinned decode math is testable end to end
                    qq, ss = quantize_kv_np(x)
                    decoded.append((qq, ss.astype(sdt)))
                else:
                    decoded.append((x.astype(dt), None))
            (k, ks), (v, vs) = decoded
            out.append((k, v, ks, vs))
        if pos != len(payload):
            raise SpillCorrupt("spill blob payload size mismatch")
        return out
    except SpillCorrupt:
        raise
    except Exception as e:  # struct errors, bad meta, short buffers
        raise SpillCorrupt(f"malformed spill blob: "
                           f"{type(e).__name__}: {e}")


class _SpillTier:
    """Byte-budgeted LRU blob store (one tier of the hierarchy).

    ``put`` evicts least-recently-used blobs into ``next_tier`` (the
    demotion chain host→disk) or drops them when this is the last
    tier; ``get`` refreshes recency. Subclasses supply the storage
    primitives. Single-threaded like the cache itself (engine-thread
    only); the occupancy counters are read racily by health probes,
    which is benign for ints."""

    name = "tier"

    def __init__(self, capacity_bytes: int, next_tier=None):
        self.capacity_bytes = int(capacity_bytes)
        self.next_tier = next_tier
        self._index: "OrderedDict[bytes, int]" = OrderedDict()
        self.occupancy_bytes = 0
        # raw-equivalent bytes per blob (r23): with coded blobs the
        # physical occupancy undersells what the tier can restore —
        # capacity/hit-rate math wants the logical figure
        self._logical: Dict[bytes, int] = {}
        self.logical_bytes = 0
        self.hits = 0
        self.misses = 0
        self.stored_blobs = 0       # lifetime puts accepted
        self.demoted_blobs = 0      # LRU-pushed into next_tier
        self.dropped_blobs = 0      # LRU/oversize-dropped (no next tier)

    # storage primitives -----------------------------------------------
    def _store(self, key: bytes, blob: bytes) -> None:
        raise NotImplementedError

    def _load(self, key: bytes) -> bytes:
        raise NotImplementedError

    def _delete(self, key: bytes) -> None:
        raise NotImplementedError

    # tier interface ---------------------------------------------------
    def contains(self, key: bytes) -> bool:
        return key in self._index

    def touch(self, key: bytes) -> None:
        if key in self._index:
            self._index.move_to_end(key)

    def _evict_lru(self) -> None:
        # account BEFORE any IO so a failed load can't corrupt the
        # occupancy books, and load the blob only when there is a next
        # tier to demote into — the last tier's budget evictions are
        # pure drops, not reads
        key, size = self._index.popitem(last=False)
        self.occupancy_bytes -= size
        self.logical_bytes -= self._logical.pop(key, size)
        if self.next_tier is None:
            self._delete(key)
            self.dropped_blobs += 1
            return
        try:
            blob = self._load(key)
        except OSError:
            # backing file vanished (same degradation get() applies):
            # the blob is already gone — drop, never raise into the
            # engine's eviction path
            self._delete(key)
            self.dropped_blobs += 1
            return
        self._delete(key)
        self.next_tier.put(key, blob)
        self.demoted_blobs += 1

    def put(self, key: bytes, blob: bytes) -> bool:
        """Store (or refresh) ``key``; returns False when the blob
        cannot fit this tier at all (it is demoted or dropped)."""
        if len(blob) > self.capacity_bytes:
            if self.next_tier is not None:
                self.next_tier.put(key, blob)
                self.demoted_blobs += 1
            else:
                self.dropped_blobs += 1
            return False
        if key in self._index:
            self.remove(key)
        while self.occupancy_bytes + len(blob) > self.capacity_bytes:
            self._evict_lru()
        self._store(key, blob)
        self._index[key] = len(blob)
        self.occupancy_bytes += len(blob)
        logical = blob_logical_bytes(blob)
        self._logical[key] = logical
        self.logical_bytes += logical
        self.stored_blobs += 1
        return True

    def get(self, key: bytes) -> Optional[bytes]:
        if key not in self._index:
            self.misses += 1
            return None
        try:
            blob = self._load(key)
        except OSError:
            # a vanished/unreadable backing file is a miss, not a
            # crash: drop the index entry and let the chained-prefill
            # fallback recompute
            size = self._index.pop(key)
            self.occupancy_bytes -= size
            self.logical_bytes -= self._logical.pop(key, size)
            self.misses += 1
            return None
        self.hits += 1
        self._index.move_to_end(key)
        return blob

    def remove(self, key: bytes) -> None:
        size = self._index.pop(key, None)
        if size is not None:
            self.occupancy_bytes -= size
            self.logical_bytes -= self._logical.pop(key, size)
            self._delete(key)

    def clear(self) -> None:
        for key in list(self._index):
            self.remove(key)

    @property
    def blob_count(self) -> int:
        return len(self._index)

    def check_consistent(self) -> None:
        """Audit: the occupancy counter matches the index, and every
        indexed blob is actually loadable (no dangling entries)."""
        total = sum(self._index.values())
        if total != self.occupancy_bytes:
            raise RuntimeError(
                f"{self.name} tier occupancy {self.occupancy_bytes} != "
                f"indexed bytes {total}")
        logical = sum(self._logical.get(k, s)
                      for k, s in self._index.items())
        if logical != self.logical_bytes:
            raise RuntimeError(
                f"{self.name} tier logical bytes {self.logical_bytes} "
                f"!= indexed logical {logical}")
        for key, size in self._index.items():
            blob = self._load(key)
            if len(blob) != size:
                raise RuntimeError(
                    f"{self.name} tier blob {key.hex()} is {len(blob)}B "
                    f"but indexed as {size}B")

    def stats(self) -> Dict[str, Any]:
        return {"blobs": self.blob_count,
                "occupancy_bytes": self.occupancy_bytes,
                "logical_bytes": self.logical_bytes,
                "capacity_bytes": self.capacity_bytes,
                "hits": self.hits, "misses": self.misses,
                "stored_blobs": self.stored_blobs,
                "demoted_blobs": self.demoted_blobs,
                "dropped_blobs": self.dropped_blobs}


class HostSpillTier(_SpillTier):
    """Host-RAM blob tier (the first spill stop for evicted pages)."""

    name = "host"

    def __init__(self, capacity_bytes: int, next_tier=None):
        super().__init__(capacity_bytes, next_tier)
        self._blobs: Dict[bytes, bytes] = {}

    def _store(self, key, blob):
        self._blobs[key] = blob

    def _load(self, key):
        return self._blobs[key]

    def _delete(self, key):
        self._blobs.pop(key, None)


class DiskSpillTier(_SpillTier):
    """Disk blob tier: one ``<key>.kvblob`` file per blob under
    ``directory``. Writes are atomic (tmp + rename) so a crash can
    never leave a half blob behind a valid index entry; construction
    scrubs stale ``*.kvblob`` files from a previous process — blobs
    never outlive the cache that wrote them (the zero-dangling-blob
    audit)."""

    name = "disk"

    def __init__(self, directory: str, capacity_bytes: int,
                 next_tier=None):
        super().__init__(capacity_bytes, next_tier)
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        for fn in os.listdir(directory):
            # .kvblob.tmp too: a crash between the tmp write and the
            # rename orphans one — restart-looping replicas must not
            # accumulate them
            if fn.endswith((".kvblob", ".kvblob.tmp")):
                try:
                    os.unlink(os.path.join(directory, fn))
                except OSError:
                    pass

    def _path(self, key: bytes) -> str:
        return os.path.join(self.directory, key.hex() + ".kvblob")

    def _store(self, key, blob):
        tmp = self._path(key) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, self._path(key))

    def _load(self, key):
        with open(self._path(key), "rb") as f:
            return f.read()

    def _delete(self, key):
        try:
            os.unlink(self._path(key))
        except OSError:
            pass

    def check_consistent(self) -> None:
        super().check_consistent()
        on_disk = {fn for fn in os.listdir(self.directory)
                   if fn.endswith(".kvblob")}
        indexed = {k.hex() + ".kvblob" for k in self._index}
        if on_disk != indexed:
            raise RuntimeError(
                f"disk tier diverged from its files: dangling "
                f"{sorted(on_disk - indexed)[:4]}, missing "
                f"{sorted(indexed - on_disk)[:4]}")


@dataclasses.dataclass
class _Entry:
    key: bytes
    parent: Optional[bytes]
    page: int
    tokens: np.ndarray            # the block's tokens (collision guard)
    refcount: int = 0             # active requests holding this one
    children: int = 0             # child entries chaining off this one
    last_used: int = 0            # LRU tick
    head: Optional[bytes] = None  # memoized chain head (r20): fixed at
                                  # insert (the parent chain never
                                  # changes), keeps the per-probe
                                  # advertisement recency pass O(N)
    dedup: bool = False           # r23: a second request proved this
                                  # page's content and folded onto it —
                                  # allocator owner is ("dedup", key),
                                  # not ("prefix", key)


class PrefixCache:
    """Host-side refcounted prefix-page cache.

    Single-threaded by design: every method runs on the engine thread
    (the server serializes engine access), matching the allocator's
    model. ``page_size`` must equal the engine's.

    Spill tiers (r15): ``spill_bytes`` adds a host-RAM tier,
    ``spill_dir`` a disk tier (of ``disk_bytes``); the host tier
    demotes into the disk tier. Tiers need device IO — the engine
    attaches its page reader/splicer via :meth:`attach_device_io` —
    and stay inert without it (a bare cache behaves exactly as
    pre-r15).

    KV byte substrate (r23): ``blob_format`` picks the transport codec
    every spill/export path packs with (``raw``/``int8``/``int4``;
    decode is format-agnostic — unpack reads the blob's own header).
    ``dedup`` folds content-identical FULL pages across unrelated
    requests onto one physical page (the chained blake2b keys prove
    content); ``blob_format="raw"`` plus ``dedup=False`` restores the
    r22 byte layout exactly."""

    def __init__(self, page_size: int, max_pages: Optional[int] = None,
                 spill_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 disk_bytes: Optional[int] = None,
                 blob_format: str = "raw",
                 dedup: bool = True,
                 generation: int = 0):
        if blob_format not in BLOB_FORMATS:
            raise ValueError(
                f"blob_format must be one of {BLOB_FORMATS}; "
                f"got {blob_format!r}")
        self.blob_format = blob_format
        # weight generation (r24 hot-swap): salted into every chain
        # root so keys from different weight generations never
        # collide/splice; 0 = boot weights, byte-identical pre-r24 keys
        self.generation = int(generation)
        self.dedup = bool(dedup)
        self.dedup_hits = 0          # pages folded onto an existing one
        # lossy-codec accounting (pack_page_blob stats sink): nonzero
        # max_abs_err is REPORTED through tier_stats/_cache_stats —
        # a lossy deployment sees its error, never silence
        self.codec_stats: Dict[str, Any] = {}
        self.page_size = int(page_size)
        # optional soft cap on cached pages; None = bounded only by
        # pool pressure (evict_until)
        self.max_pages = max_pages
        self._entries: Dict[bytes, _Entry] = {}
        self._tick = 0
        # lifetime counters (serving/metrics.py scrapes these through
        # the engine's RequestStats; kept here too for direct audits).
        # hit/miss_pages stay DEVICE-tier figures; tier hits land in
        # tier_hit_pages and hit_rate() blends all tiers.
        self.hit_pages = 0
        self.miss_pages = 0
        self.inserted_pages = 0
        self.evicted_pages = 0
        # spill-tier counters (r15)
        self.tier_hit_pages: Dict[str, int] = {}
        self.spilled_pages = 0       # blobs written on eviction
        self.restored_pages = 0      # blobs spliced back on a hit
        self.restore_corrupt = 0     # crc/structure failures (typed)
        self.spill_failed = 0        # spill writes lost (fault/io)
        self.last_restore_ms: Optional[float] = None
        # spill-tier chain (host -> disk); [] = spill disabled
        disk = (DiskSpillTier(spill_dir,
                              int(disk_bytes
                                  if disk_bytes is not None
                                  else 1 << 30))
                if spill_dir else None)
        host = (HostSpillTier(int(spill_bytes), next_tier=disk)
                if spill_bytes else None)
        self.tiers: List[_SpillTier] = [t for t in (host, disk)
                                        if t is not None]
        for t in self.tiers:
            self.tier_hit_pages[t.name] = 0
        # device IO installed by the engine (attach_device_io):
        # read_page(page) -> per-layer (k, v, ks, vs) host arrays;
        # splice_page(page, layers) writes them back into fresh pages
        self._read_page: Optional[Callable[[int], Any]] = None
        self._splice_page: Optional[Callable[[int, Any], None]] = None
        # chain-head keys currently represented in a tier (the router's
        # affinity advertisement also covers spilled-but-restorable
        # prefixes); pruned lazily in advertised_keys()
        self._tier_heads: set = set()
        # disaggregated serving (r20): chain membership of spilled
        # entries by head key. Eviction is leaf-first, so at spill time
        # the parent path is still device-resident and the head is
        # computable — this is what lets fetch_pages expand a head into
        # its full chain even after parts of it left the device tier.
        self._spilled_by_head: Dict[bytes, set] = {}
        # keys whose tier blobs arrived over the WIRE (fetch_pages
        # import) rather than from a local eviction — consumed by
        # restore_from_spill to report the fetched-vs-restored split
        self._fetched_keys: set = set()
        # lifetime wire-handoff counters (r20)
        self.exported_pages = 0      # blobs served to peers
        self.imported_pages = 0      # blobs accepted from peers
        self.import_corrupt = 0      # wire blobs failing re-verify

    # -- spill-tier plumbing ------------------------------------------------

    def attach_device_io(self, read_page: Callable[[int], Any],
                         splice_page: Callable[[int, Any], None]
                         ) -> None:
        """Engine hookup: how the cache copies a page device→host at
        eviction (``read_page(page) -> per-layer blocks``) and splices
        a run of restored blobs back into fresh pages
        (``splice_page(pages, layers_list)`` — BATCHED: one device
        call restores the whole contiguous chain run;
        inference/continuous_batching.py)."""
        self._read_page = read_page
        self._splice_page = splice_page

    @property
    def spill_enabled(self) -> bool:
        return bool(self.tiers) and self._read_page is not None

    # -- keys --------------------------------------------------------------

    def _shareable_blocks(self, prompt: np.ndarray) -> int:
        # full pages strictly before the last prompt token: guarantees
        # a non-empty suffix prefill (see module docstring)
        return max(0, (len(prompt) - 1) // self.page_size)

    def _chain_keys(self, prompt: np.ndarray
                    ) -> List[Tuple[bytes, Optional[bytes], np.ndarray]]:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        out: List[Tuple[bytes, Optional[bytes], np.ndarray]] = []
        parent: Optional[bytes] = None
        for i in range(self._shareable_blocks(prompt)):
            block = prompt[i * self.page_size:(i + 1) * self.page_size]
            key = _block_hash(parent, block,
                              generation=self.generation)
            out.append((key, parent, block))
            parent = key
        return out

    # -- lookup / refcounts ------------------------------------------------

    def _memo_chain(self, prompt, memo=None
                    ) -> List[Tuple[bytes, Optional[bytes], np.ndarray]]:
        chain = getattr(memo, "_pfx_chain", None) if memo is not None \
            else None
        if chain is None:
            chain = self._chain_keys(prompt)
            if memo is not None:
                memo._pfx_chain = chain
        return chain

    def match(self, prompt, memo=None
              ) -> Tuple[Tuple[bytes, ...], List[int]]:
        """Longest cached prefix for ``prompt``: (chain keys, pages).
        Pure — no refcounts move (admission calls ``acquire`` once it
        commits; ``_fits`` probes freely). ``memo`` (typically the
        DecodeRequest) caches the chain hashes across calls — the
        prompt is immutable, and per-step admission probes must cost
        dict lookups, not O(prompt) re-hashing."""
        chain = self._memo_chain(prompt, memo)
        keys: List[bytes] = []
        pages: List[int] = []
        for key, _parent, block in chain:
            ent = self._entries.get(key)
            if ent is None or not np.array_equal(ent.tokens, block):
                break  # miss (or hash collision — treated as a miss)
            keys.append(key)
            pages.append(ent.page)
        return tuple(keys), pages

    def acquire(self, keys: Sequence[bytes]) -> None:
        """Pin a matched chain for an admitting request (one ref per
        entry). Hit/miss stats are counted once, at ``insert`` (an
        admission that later unwinds releases without skewing them)."""
        self._tick += 1
        for k in keys:
            ent = self._entries[k]
            ent.refcount += 1
            ent.last_used = self._tick

    def release(self, keys: Sequence[bytes]) -> None:
        for k in keys:
            ent = self._entries.get(k)
            if ent is None:
                continue  # entry force-cleared (close() teardown)
            ent.refcount -= 1
            if ent.refcount < 0:
                raise RuntimeError(
                    f"prefix-cache refcount underflow on {k.hex()}")

    # -- spill / restore (r15) ---------------------------------------------

    def _spill_entry(self, ent: _Entry) -> None:
        """Copy an about-to-be-evicted entry's page device→host into
        the first spill tier. Tiers are INCLUSIVE of the device tier:
        a page restored earlier still has its blob, so re-eviction is
        an LRU touch, not a second device read. A failed/injected
        spill write just loses the content (a later match degrades to
        a miss) — never an error on the eviction path."""
        if not self.spill_enabled:
            return
        for t in self.tiers:
            if t.contains(ent.key):
                t.touch(ent.key)
                return
        from ..distributed.fault_inject import (InjectedFault,
                                                fault_point)
        try:
            # cache.spill write side: "abort" loses the blob (counted,
            # degrades to a miss), "torn" stores a corrupted blob the
            # restore-side crc32 must catch
            mode = fault_point("cache.spill", modes=("abort", "torn"))
        except InjectedFault:
            self.spill_failed += 1
            return
        try:
            blob = pack_page_blob(self._read_page(ent.page),
                                  fmt=self.blob_format,
                                  stats=self.codec_stats)
        except Exception:
            self.spill_failed += 1
            return
        if mode == "torn":
            # flip one payload byte; the header/crc stay intact so the
            # corruption is only detectable by the crc32 check
            blob = blob[:-1] + bytes([blob[-1] ^ 0xFF])
        self.tiers[0].put(ent.key, blob)
        self.spilled_pages += 1
        head = self._head_of(ent.key)
        if head is not None:
            self._spilled_by_head.setdefault(head, set()).add(ent.key)
        if ent.parent is None:
            self._tier_heads.add(ent.key)

    def restore_from_spill(self, prompt, matched_keys: Sequence[bytes],
                           allocator, memo=None
                           ) -> Tuple[Tuple[bytes, ...], List[int],
                                      Dict[str, Any]]:
        """Extend a device-tier ``match`` by restoring spilled blobs:
        walk the prompt's chain past the device hits collecting
        contiguous tier hits (crc-verified), allocate one fresh page
        per blob (owner ``("prefix", key)`` — the cache's books,
        exactly like an inserted page), splice ALL of them back in ONE
        batched device call (the whole-restore cost is one device_put
        plus one scatter launch, not one per page), and register the
        device entries. The walk stops at the first tier miss (the
        chained-prefill fallback covers the rest), allocation failure,
        or corrupt blob (typed + counted — never spliced). Returns
        (restored keys, their pages, info) where info carries per-tier
        page counts, corrupt count, and the restore wall time in ms.
        Caller acquires the full chain afterwards, exactly like device
        hits."""
        info: Dict[str, Any] = {t.name: 0 for t in self.tiers}
        info.update(corrupt=0, ms=0.0, fetched=0)
        if not self.spill_enabled or self._splice_page is None:
            return (), [], info
        chain = self._memo_chain(prompt, memo)
        start = len(matched_keys)
        if start >= len(chain):
            return (), [], info
        from ..distributed.fault_inject import (InjectedFault,
                                                fault_point)
        t0 = time.perf_counter()
        # phase 1: walk the tiers host-side — which contiguous run of
        # blobs is restorable, and what do they decode to
        hits: List[Tuple[bytes, Optional[bytes], np.ndarray, str,
                         Any]] = []
        for i in range(start, len(chain)):
            key, parent, block = chain[i]
            if key in self._entries:
                break  # collision with different tokens (match missed)
            blob = None
            tier = None
            for t in self.tiers:
                blob = t.get(key)
                if blob is not None:
                    tier = t
                    break
            if blob is None:
                break  # tier miss mid-chain: chained prefill takes over
            try:
                # cache.spill read side: an injected read failure is a
                # typed miss — the fallback prefill recomputes the page
                fault_point("cache.spill")
            except InjectedFault:
                self.spill_failed += 1
                break
            try:
                layers = unpack_page_blob(blob)
            except SpillCorrupt:
                tier.remove(key)
                self.restore_corrupt += 1
                info["corrupt"] += 1
                break
            hits.append((key, parent, block, tier.name, layers))
        # phase 2: bind pages for the whole run (per-key owners so the
        # allocator books stay page-exact), splice ONCE, register.
        # Allocation applies EVICTION PRESSURE: a restore is the cache
        # choosing to hold the ACTIVE prefix, so cold refcount-0
        # chains make way (and spill in turn — usually an LRU touch,
        # their blobs already exist). The caller pinned its
        # device-matched chain BEFORE restoring, so eviction can never
        # reclaim pages this admission is about to use.
        def alloc_one(key):
            while True:
                try:
                    pages = allocator.alloc(("prefix", key), 1)
                except InjectedFault:
                    return None  # alloc.page chaos: same as no space
                if pages is not None:
                    return pages
                if not self._evict_one(allocator):
                    return None

        new_keys: List[bytes] = []
        new_pages: List[int] = []
        for key, _parent, _block, _tname, _layers in hits:
            if self.max_pages is not None and \
                    self.total_pages() + len(new_keys) >= \
                    self.max_pages and \
                    not self._evict_one(allocator):
                break  # soft cap (same rule as insert())
            pages = alloc_one(key)
            if not pages:
                break
            new_keys.append(key)
            new_pages.append(pages[0])
        hits = hits[:len(new_keys)]
        if hits:
            try:
                self._splice_page(new_pages,
                                  [h[4] for h in hits])
            except Exception:
                # a failed splice must not leak the fresh pages
                for key in new_keys:
                    allocator.free(("prefix", key))
                raise
            for (key, parent, block, tname, _layers), page in \
                    zip(hits, new_pages):
                self._tick += 1
                self._entries[key] = _Entry(key, parent, page,
                                            np.array(block, np.int32),
                                            refcount=0,
                                            last_used=self._tick,
                                            head=self._memo_head(
                                                key, parent))
                if parent is not None:
                    self._entries[parent].children += 1
                self.tier_hit_pages[tname] += 1
                info[tname] += 1
                if key in self._fetched_keys:
                    # this page's blob arrived over the wire (r20
                    # handoff) — the fetched-vs-restored split the
                    # trace span and RequestStats report
                    info["fetched"] += 1
                    self._fetched_keys.discard(key)
        if new_keys or info["corrupt"]:
            ms = (time.perf_counter() - t0) * 1e3
            info["ms"] = ms
            self.last_restore_ms = ms
            self.restored_pages += len(new_keys)
        return tuple(new_keys), new_pages, info

    # -- wire handoff (r20 disaggregated serving) ----------------------------

    def _memo_head(self, key: bytes, parent: Optional[bytes]
                   ) -> bytes:
        """Chain head for a new entry: the parent's memoized head (the
        parent is resident at insert — chains build root-first), else
        this key IS the head."""
        if parent is None:
            return key
        pent = self._entries.get(parent)
        if pent is not None and pent.head is not None:
            return pent.head
        return self._walk_head(parent) or key

    def _head_of(self, key: bytes) -> Optional[bytes]:
        """Chain head of a resident entry — the insert-time memo, with
        the parent walk as a defensive fallback."""
        ent = self._entries.get(key)
        if ent is None:
            return None
        if ent.head is not None:
            return ent.head
        return self._walk_head(key)

    def _walk_head(self, key: bytes) -> Optional[bytes]:
        """Walk parents to the chain head. Eviction is leaf-first, so
        every device entry's parent path is fully resident — the walk
        only returns None on a key the cache does not know."""
        ent = self._entries.get(key)
        if ent is None:
            return None
        while ent.parent is not None:
            parent = self._entries.get(ent.parent)
            if parent is None:
                return None  # defensive: cannot happen leaf-first
            ent = parent
        return ent.key

    def _tier_blob(self, key: bytes) -> Optional[bytes]:
        """Read a tier blob WITHOUT touching the tier hit/miss stats
        (those describe restore traffic; wire exports are a different
        consumer). Recency is still refreshed — a chain being handed
        off is hot by definition."""
        for t in self.tiers:
            if t.contains(key):
                try:
                    blob = t._load(key)
                except OSError:
                    continue
                t.touch(key)
                return blob
        return None

    def chain_keys_for(self, prompt) -> List[bytes]:
        """The prompt's full chain keys (pure hashing, no state) — how
        a decode-class replica names the pages it wants to fetch."""
        return [k for k, _p, _b in self._chain_keys(prompt)]

    def expand_heads(self, heads: Sequence[bytes]) -> List[bytes]:
        """Every chain key reachable from ``heads``: the device-tier
        subtree (via a reverse child index) plus members recorded at
        spill time (``_spilled_by_head``). This is how ``fetch_pages``
        serves a whole chain when the caller only knows the advertised
        head (the drain-handoff path)."""
        children: Dict[bytes, List[bytes]] = {}
        for e in self._entries.values():
            if e.parent is not None:
                children.setdefault(e.parent, []).append(e.key)
        out: List[bytes] = []
        seen: set = set()
        for head in heads:
            stack = [head]
            while stack:
                k = stack.pop()
                if k in seen:
                    continue
                seen.add(k)
                out.append(k)
                stack.extend(children.get(k, ()))
            for k in sorted(self._spilled_by_head.get(head, ())):
                if k not in seen:
                    seen.add(k)
                    out.append(k)
        return out

    def export_blobs(self, keys: Sequence[bytes]
                     ) -> Tuple[Dict[bytes, bytes], List[bytes]]:
        """Serve chain pages to a peer replica (the ``fetch_pages``
        wire op, engine thread): tier blobs are returned as stored
        (their crc travels with them), device-resident pages are
        packed fresh through the same ``pack_page_blob`` format the
        spill path writes. Returns (blobs by key, missing keys) —
        a key this cache cannot produce is MISSING, never an error
        (the peer's chained-prefill fallback covers it)."""
        blobs: Dict[bytes, bytes] = {}
        missing: List[bytes] = []
        for key in keys:
            blob = self._tier_blob(key) if self.tiers else None
            if blob is None:
                ent = self._entries.get(key)
                if ent is not None and self._read_page is not None:
                    try:
                        blob = pack_page_blob(
                            self._read_page(ent.page),
                            fmt=self.blob_format,
                            stats=self.codec_stats)
                    except Exception:
                        blob = None
            if blob is None:
                missing.append(key)
            else:
                blobs[key] = blob
                self.exported_pages += 1
        return blobs, missing

    def import_blobs(self, blobs: Dict[bytes, bytes],
                     heads: Sequence[bytes] = ()) -> Dict[str, int]:
        """Accept fetched chain pages from a peer (decode-replica side
        of the handoff, engine thread): every blob is crc RE-VERIFIED on
        receipt (a torn wire transfer is a counted skip, never spliced
        KV), keys already device-resident are skipped, and the rest
        land in the first spill tier exactly like a local eviction —
        the existing ``restore_from_spill`` splice path picks them up
        at admission. ``heads`` marks chain heads for the affinity
        advertisement. Returns {imported, corrupt, skipped, dropped,
        bytes} — ``dropped`` counts blobs the byte-budgeted tiers
        could not keep (they re-fetch or re-prefill on first use),
        so the reply never claims pages that did not land."""
        report = {"imported": 0, "corrupt": 0, "skipped": 0,
                  "dropped": 0, "bytes": 0}
        if not self.tiers:
            report["skipped"] = len(blobs)
            return report
        # lazy bound on the fetched-key record: a wire blob the tier
        # LRU has since evicted can never be restored, so its
        # fetched-split marker is dead weight on a long-lived replica
        if self._fetched_keys:
            self._fetched_keys = {
                k for k in self._fetched_keys
                if any(t.contains(k) for t in self.tiers)}
        landed = []
        for key, blob in blobs.items():
            if key in self._entries:
                report["skipped"] += 1
                continue
            try:
                unpack_page_blob(blob)
            except SpillCorrupt:
                self.import_corrupt += 1
                report["corrupt"] += 1
                continue
            self.tiers[0].put(key, blob)
            landed.append((key, len(blob)))
        # count (and mark) only blobs resident AFTER the whole batch:
        # put() may demote to a deeper tier or drop an oversize blob
        # outright, and a LATER blob's put can LRU-evict an earlier
        # import — the reply must never claim pages that did not land
        for key, nbytes in landed:
            if not any(t.contains(key) for t in self.tiers):
                report["dropped"] += 1
                continue
            self._fetched_keys.add(key)
            self.imported_pages += 1
            report["imported"] += 1
            report["bytes"] += nbytes
        for h in heads:
            if any(t.contains(h) for t in self.tiers):
                self._tier_heads.add(h)
        return report

    # -- insertion ---------------------------------------------------------

    def insert(self, prompt, row: np.ndarray, allocator, owner: Hashable,
               page_size: int, matched_keys: Sequence[bytes],
               device_hits: Optional[int] = None) -> Tuple[bytes, ...]:
        """Adopt the freshly-prefilled full prompt pages of ``row``
        into the cache (ownership transfer ``owner`` → cache) and
        return the request's full chain keys (matched + new), each
        holding one reference for the request.

        ``row`` is the slot's page-table row: entry i is the physical
        page of token block i, so the new blocks' pages are read
        straight out of it.

        ``device_hits``: how many of ``matched_keys`` were DEVICE-tier
        hits (the rest were restored from spill and already counted
        per-tier at restore time); None = all of them (the pre-r15
        single-tier accounting)."""
        if page_size != self.page_size:
            raise ValueError(
                f"engine page_size {page_size} != cache page_size "
                f"{self.page_size}")
        chain = self._chain_keys(prompt)
        keys: List[bytes] = list(matched_keys)
        self.hit_pages += (len(matched_keys) if device_hits is None
                           else int(device_hits))
        self.miss_pages += max(0, len(chain) - len(matched_keys))
        for i in range(len(matched_keys), len(chain)):
            key, parent, block = chain[i]
            ent = self._entries.get(key)
            if ent is not None and np.array_equal(ent.tokens, block):
                # already cached: a sibling request with the same
                # prefix prefilled concurrently (its insert landed
                # between our match() and now) — take a reference.
                ent.refcount += 1
                ent.last_used = self._tick
                keys.append(key)
                if self.dedup:
                    # r23 cross-request dedup: the chained key plus
                    # the token-equality check above prove our private
                    # page holds byte-identical KV (a FULL page is an
                    # immutable function of the chain) — retarget the
                    # table row at the shared page and return the
                    # duplicate to the free list. The shared page
                    # moves to a ("dedup", key) owner so occupancy()
                    # reports cross-request shared pages as a class.
                    page = int(row[i])
                    owned = allocator.owners().get(owner, ())
                    if page != ent.page and page in owned:
                        led = getattr(allocator, "ledger", None)
                        ctx = (led.why("dedup_hit",
                                       owner if isinstance(owner, int)
                                       else None)
                               if led is not None
                               else contextlib.nullcontext())
                        with ctx:
                            row[i] = ent.page  # row aliases _table[slot]
                            allocator.release_pages(owner, [page])
                            if not ent.dedup:
                                allocator.transfer(
                                    ("prefix", ent.key),
                                    ("dedup", ent.key), [ent.page])
                                ent.dedup = True
                        self.dedup_hits += 1
                continue
            if ent is not None:
                break  # hash collision with different tokens: stop
            if self.max_pages is not None and \
                    self.total_pages() >= self.max_pages and \
                    not self._evict_one(allocator):
                break  # soft cap reached and nothing evictable
            page = int(row[i])
            allocator.transfer(owner, ("prefix", key), [page])
            self._tick += 1
            self._entries[key] = _Entry(key, parent, page,
                                        np.array(block, np.int32),
                                        refcount=1, last_used=self._tick,
                                        head=self._memo_head(key, parent))
            if parent is not None:
                self._entries[parent].children += 1
            self.inserted_pages += 1
            keys.append(key)
        return tuple(keys)

    # -- eviction ----------------------------------------------------------

    def _evictable(self) -> List[_Entry]:
        return [e for e in self._entries.values()
                if e.refcount == 0 and e.children == 0]

    def evictable_pages(self, excluding: Sequence[bytes] = ()) -> int:
        """Pages reclaimable RIGHT NOW plus transitively (a refcount-0
        parent becomes evictable once its refcount-0 leaves go): every
        entry not pinned by some active request at or below it.
        ``excluding`` marks entries the CALLER is about to pin (its own
        prefix match) — counting those as evictable would make
        admission-fit checks optimistic about pages that the admission
        itself takes off the table."""
        pinned: set = set()
        for start in list(excluding):
            k: Optional[bytes] = start
            while k is not None and k not in pinned and \
                    k in self._entries:
                pinned.add(k)
                k = self._entries[k].parent
        for e in self._entries.values():
            if e.refcount > 0:
                k = e.key
                while k is not None and k not in pinned:
                    pinned.add(k)
                    k = self._entries[k].parent
        return len(self._entries) - len(pinned)

    @staticmethod
    def _owner_of(ent: _Entry) -> Tuple[str, bytes]:
        """The allocator owner this entry's page sits under: dedup'd
        pages moved to ("dedup", key) when a second request folded
        onto them (r23); everything else stays ("prefix", key)."""
        return ("dedup" if ent.dedup else "prefix", ent.key)

    def _evict_one(self, allocator) -> bool:
        cands = self._evictable()
        if not cands:
            return False
        victim = min(cands, key=lambda e: e.last_used)
        # r15: eviction spills before it frees — the page's content
        # survives as a host/disk blob a later match can restore
        self._spill_entry(victim)
        allocator.free(self._owner_of(victim))
        if victim.parent is not None:
            self._entries[victim.parent].children -= 1
        del self._entries[victim.key]
        self.evicted_pages += 1
        return True

    def evict_until(self, allocator, need_free: int) -> bool:
        """LRU-evict refcount-0 leaves until the allocator has
        ``need_free`` free pages (True) or nothing evictable remains
        (False)."""
        while allocator.free_count < need_free:
            if not self._evict_one(allocator):
                return False
        return True

    def clear(self, allocator) -> None:
        """Return every cached page to the allocator (engine close()).
        Active references must already be gone — a nonzero refcount
        here is a lifecycle bug, not cache pressure."""
        busy = [e for e in self._entries.values() if e.refcount > 0]
        if busy:
            raise RuntimeError(
                f"prefix-cache clear with {len(busy)} entries still "
                f"referenced (refcounts "
                f"{[e.refcount for e in busy[:8]]}) — release requests "
                f"before close()")
        for ent in self._entries.values():
            allocator.free(self._owner_of(ent))
        self.evicted_pages += len(self._entries)
        self._entries.clear()
        # spill blobs die with the cache: every exit path must leave
        # zero dangling tier blobs (disk files included)
        for t in self.tiers:
            t.clear()
        self._tier_heads.clear()
        self._spilled_by_head.clear()
        self._fetched_keys.clear()

    def set_generation(self, generation: int, allocator) -> None:
        """Weight hot-swap (r24): move the cache to a new weight
        generation. Every resident page, spill blob, and dedup fold
        was computed by the OLD weights, so the whole cache is cleared
        (pages back to the allocator, tier blobs scrubbed) and future
        chain roots are salted with the new generation — old-key
        lookups miss by construction even against a peer that still
        holds them. Requires a drained cache (refcount-0 everywhere):
        the engine swaps weights only with no active requests, so a
        busy entry here is a lifecycle bug and ``clear`` raises."""
        generation = int(generation)
        if generation == self.generation:
            return
        self.clear(allocator)
        self.generation = generation

    # -- audits ------------------------------------------------------------

    def total_pages(self) -> int:
        return len(self._entries)

    def hit_rate(self) -> Optional[float]:
        """Blended hit rate across ALL tiers: device hits plus restored
        spill hits over everything the cache was asked for. Per-tier
        figures live in :meth:`tier_stats`."""
        hits = self.hit_pages + sum(self.tier_hit_pages.values())
        seen = hits + self.miss_pages
        return hits / seen if seen else None

    def tier_stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-tier counters for metrics/stats export: the device tier
        (resident pages, hit/miss pages) plus each spill tier's
        occupancy and hit accounting."""
        out: Dict[str, Dict[str, Any]] = {
            "device": {"pages": len(self._entries),
                       "hit_pages": self.hit_pages,
                       "miss_pages": self.miss_pages,
                       "dedup_pages": sum(
                           1 for e in self._entries.values()
                           if e.dedup),
                       "dedup_hits": self.dedup_hits}}
        for t in self.tiers:
            s = t.stats()
            s["hit_pages"] = self.tier_hit_pages.get(t.name, 0)
            out[t.name] = s
        return out

    def advertised_keys(self, limit: int = 128) -> List[str]:
        """Back-compat wrapper over :meth:`advertised_keys_info`."""
        return self.advertised_keys_info(limit)["keys"]

    def advertised_keys_info(self, limit: int = 128) -> Dict[str, Any]:
        """Chain-HEAD keys (hex) this cache can serve a prefix for —
        device-resident heads plus heads whose blob still sits in a
        spill tier — ordered by the most recent touch ANYWHERE in the
        head's chain (r20 fix: a head entry's own ``last_used`` goes
        stale the moment traffic only touches deeper blocks, which
        made a hot deep chain fall off a truncated advertisement
        first). Returns ``{"keys": [...], "truncated": bool}`` so the
        router can distinguish "not resident" from "not advertised"
        on a replica holding more heads than ``limit``. This is the
        affinity advertisement the server's health reply carries and
        the failover router steers on (serving/supervisor.py); it is
        a routing HINT, so staleness is benign."""
        # recency of a head = max last_used over its chain: one parent
        # walk per entry (leaf-first eviction keeps parent paths
        # resident, so the walk always terminates at a head)
        recency: Dict[bytes, int] = {}
        for e in self._entries.values():
            head = self._head_of(e.key)
            if head is not None:
                recency[head] = max(recency.get(head, 0), e.last_used)
        ordered = sorted(recency, key=lambda k: -recency[k])
        out = [k.hex() for k in ordered[:limit]]
        seen = set(out)
        extra = 0
        for k in list(self._tier_heads):
            if k in self._entries:
                continue  # already advertised (or will be) as device
            if any(t.contains(k) for t in self.tiers):
                if k.hex() in seen:
                    continue
                if len(out) < limit:
                    out.append(k.hex())
                    seen.add(k.hex())
                else:
                    extra += 1
            else:
                # the head's blob left every tier: drop it from the
                # advertisement AND its spilled-chain membership record
                self._tier_heads.discard(k)
                self._spilled_by_head.pop(k, None)
        return {"keys": out,
                "truncated": bool(len(ordered) > limit or extra)}

    def check_consistent(self, allocator) -> None:
        """Drained-engine audit: every page the allocator still sees as
        owned must be a cache page, and the books must balance —
        free + cached == pool size. The with-cache analog of
        ``PageAllocator.check_no_leak``."""
        owners = allocator.owners()
        cache_owned = 0
        for owner, pages in owners.items():
            if not (isinstance(owner, tuple) and len(owner) == 2
                    and owner[0] in ("prefix", "dedup")):
                raise RuntimeError(
                    f"page leak past drain: owner {owner!r} still holds "
                    f"{list(pages)}")
            ent = self._entries.get(owner[1])
            if ent is None or tuple(pages) != (ent.page,):
                raise RuntimeError(
                    f"prefix-cache books diverge from allocator for "
                    f"owner {owner!r}: allocator={list(pages)}, "
                    f"entry={ent}")
            if (owner[0] == "dedup") != ent.dedup:
                raise RuntimeError(
                    f"dedup books diverge for {owner!r}: allocator "
                    f"class {owner[0]!r} but entry.dedup={ent.dedup}")
            cache_owned += len(pages)
        if allocator.free_count + cache_owned != allocator.num_pages:
            raise RuntimeError(
                f"page accounting broken: {allocator.free_count} free + "
                f"{cache_owned} cached != pool {allocator.num_pages}")
        # spill tiers: occupancy counters match the stored blobs and
        # (disk) the files on disk — no dangling blobs
        for t in self.tiers:
            t.check_consistent()
