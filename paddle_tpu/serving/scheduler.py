"""SLO-aware admission scheduling for the serving layer.

Replaces the engine's built-in blocking FIFO (admit the head or admit
nothing) with a policy that knows about service classes:

- **Priority classes** (`Priority`): INTERACTIVE > NORMAL > BATCH.
  Higher classes are admitted first when several requests fit.
- **Max-queue-delay promotion**: a request that has waited longer than
  ``promote_after_s`` gains one effective priority level per elapsed
  interval (capped at INTERACTIVE), so BATCH work cannot wait forever
  behind a steady INTERACTIVE stream.
- **Bounded fairness**: admitting a later request over an earlier one
  increments the earlier request's ``bypass_count``; once any request
  has been bypassed ``max_bypass`` times it becomes the only admissible
  candidate until it fits. Long prompts therefore cannot starve short
  ones (short ones keep flowing while the long one's pages free up),
  and short ones cannot starve the long head indefinitely (the bypass
  bound eventually reserves the free list for it).
- **Overload shedding**: requests queued past ``shed_after_s`` (and,
  at submit time, beyond ``max_queue`` depth) are rejected with the
  typed `ServerOverloaded` — the server turns it into a structured
  error reply instead of an ever-growing queue of doomed work.
- **Chunk-budget policy** (r11 chunked prefill): ``select_chunk``
  decides whether the engine's per-step prefill budget (one chunk of
  one half-prefilled slot) runs or yields — INTERACTIVE decode steps
  preempt lower-class prefill chunks so a BATCH 8k-prompt can't dent
  interactive TPOT, bounded by ``max_chunk_deferrals`` so the prefill
  still finishes. ``max_prefill_debt_tokens`` caps each class's
  in-flight half-prefilled debt at admission (the engine's
  ``_debt_allows`` gate), so a stream of long prompts can't turn every
  slot into prefill work at once.

The scheduler is duck-typed against the engine
(``select(queue, fits, now)`` / ``shed(queue, now)``), so the engine
stays importable without the serving package.

Reference analog: the multi-stream priority scheduling of the
reference's serving stack, rebuilt host-side over one jitted step.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, List, Optional

__all__ = ["Priority", "SLOConfig", "SLOScheduler", "ServerOverloaded"]


class Priority(enum.IntEnum):
    BATCH = 0
    NORMAL = 1
    INTERACTIVE = 2


class ServerOverloaded(RuntimeError):
    """Typed admission rejection: the queue is past its SLO. Carries a
    client-actionable retry hint; the server serializes it as
    ``{"error": "ServerOverloaded", "reason": ..., "retry_after_ms":
    ...}``."""

    def __init__(self, reason: str, retry_after_ms: int = 1000):
        super().__init__(reason)
        self.reason = reason
        self.retry_after_ms = int(retry_after_ms)


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    # one effective priority level gained per this many seconds queued
    promote_after_s: float = 1.0
    # queued longer than this -> shed with ServerOverloaded (None = never)
    shed_after_s: Optional[float] = 30.0
    # submit-time depth bound (None = unbounded); checked by the server
    max_queue: Optional[int] = None
    # how many times a queued request may be jumped before it becomes
    # the mandatory next admission
    max_bypass: int = 4
    retry_after_ms: int = 1000
    # chunked prefill (r11): consecutive ENGINE BOUNDARIES a
    # lower-class prefill chunk may be deferred by higher-class decode
    # before it runs anyway (the starvation bound of
    # decode-preempts-prefill). Units are engine step() calls — with
    # multi-step decode (r19, multi_step=N) each boundary covers up
    # to N generated tokens, so a deferral budget of 4 means up to
    # 4*N decode tokens of delay, not 4; TTFT-sensitive deployments
    # running large N should shrink this accordingly. With the r22
    # in-program inner loop a GRANT costs decode nothing (the chunks
    # ride inside the macro launch, one per iteration, instead of
    # stalling the boundary) and each grant advances up to N chunks,
    # so deferring is only worth it when the launch itself must stay
    # small — the default budget is then an upper bound, not a tune.
    max_chunk_deferrals: int = 4
    # per-class cap on in-flight half-prefilled debt (tokens) at
    # admission; None = unbounded. A class with zero in-flight debt is
    # always admissible (the cap bounds concurrency, never locks a
    # class out).
    max_prefill_debt_tokens: Optional[int] = None
    # disaggregated serving (r20): priority levels granted to a
    # HANDOFF-BLOCKING prefill job (a prefill-class replica's
    # prefill_only request — the router is mid-handoff and a decode
    # replica is literally waiting on the chain, so it must not queue
    # behind a BATCH backlog). Capped at INTERACTIVE like promotion;
    # 0 restores the pre-r20 ordering.
    handoff_boost: int = 1


class SLOScheduler:
    """Admission policy over the engine's wait queue.

    ``select`` returns the queue INDEX to admit next (or None to admit
    nothing this step); ``shed`` returns the requests to reject. Both
    run on the engine thread; ``check_admission`` is the submit-time
    depth gate and may run on server connection threads (it only reads
    the depth it is handed)."""

    def __init__(self, config: Optional[SLOConfig] = None):
        self.cfg = config or SLOConfig()

    # -- submit-time gate --------------------------------------------------

    def check_admission(self, queued: int) -> None:
        cfg = self.cfg
        if cfg.max_queue is not None and queued >= cfg.max_queue:
            raise ServerOverloaded(
                f"queue depth {queued} at max_queue {cfg.max_queue}",
                retry_after_ms=cfg.retry_after_ms)

    # -- engine hooks ------------------------------------------------------

    def effective_priority(self, req, now: float) -> int:
        waited = max(0.0, now - req.stats.submit_t)
        promo = int(waited / self.cfg.promote_after_s) \
            if self.cfg.promote_after_s > 0 else 0
        # handoff-blocking prefill jobs (r20) jump handoff_boost
        # levels: a decode replica is stalled on this chain
        boost = (self.cfg.handoff_boost
                 if getattr(req, "handoff", False) else 0)
        return min(int(Priority.INTERACTIVE),
                   req.priority + promo + boost)

    def select(self, queue: List, fits: Callable[[object], bool],
               now: float) -> Optional[int]:
        if not queue:
            return None
        cfg = self.cfg
        # fairness bound: a request bypassed too often is the only
        # admissible candidate until it fits
        starved = [r for r in queue
                   if r.bypass_count >= cfg.max_bypass]
        pool = starved if starved else list(queue)
        # stable order: effective priority desc, then earliest deadline
        # (requests without one sort last within their class), then
        # arrival — EDF inside a class so a tight deadline_ms is spent
        # queueing as little as possible
        pool.sort(key=lambda r: (
            -self.effective_priority(r, now),
            getattr(r, "deadline_t", None)
            if getattr(r, "deadline_t", None) is not None
            else float("inf"),
            r.stats.submit_t))
        for cand in pool:
            if fits(cand):
                return queue.index(cand)
        return None

    def explain(self, req, now: float) -> dict:
        """Queue-delay attribution for the tracer (r16): WHY this
        request waited — its class, any promotion it earned, and how
        often it was bypassed. Duck-typed: the engine attaches this to
        the queue span's close when the scheduler provides it."""
        eff = self.effective_priority(req, now)
        out = {"priority": int(req.priority),
               "effective_priority": int(eff),
               "promoted": bool(eff > req.priority),
               "waited_ms": round(
                   max(0.0, now - req.stats.submit_t) * 1e3, 3)}
        if getattr(req, "handoff", False):
            out["handoff"] = True  # handoff-blocking prefill (r20)
        return out

    def note_admitted(self, req, queue: List, now: float) -> None:
        """Called by the engine AFTER an admission COMMITS: charge one
        bypass to every earlier-arrived request still queued. Charging
        here (not in ``select``) keeps a failed/unwound admission from
        accumulating phantom bypasses that would flip the queue into
        starved-only mode without any real jump having happened."""
        for other in queue:
            if other.stats.submit_t < req.stats.submit_t:
                other.bypass_count += 1

    def select_chunk(self, partial: List, decoding: List,
                     now: float) -> Optional[int]:
        """Chunk-budget policy (r11 chunked prefill), called by the
        engine once per step: ``partial`` is [(slot, request)] for
        every half-prefilled slot, ``decoding`` the requests past
        prefill. Returns the slot whose next chunk should run, or None
        to yield this step's budget to pure decode.

        INTERACTIVE decode preempts lower-class prefill chunks (the
        step stays a pure decode step, so interactive TPOT never pays
        for a BATCH prompt's prefill), but only ``max_chunk_deferrals``
        times in a row — then the chunk runs regardless, so the long
        prompt still finishes (the bypass-bound idea applied to the
        prefill budget). With nothing decoding there is nothing to
        protect: the top-ranked chunk always runs (the engine relies
        on this for drain progress).

        Multi-step decode (r19): this hook runs once per BOUNDARY, so
        under ``multi_step=N`` each deferral costs up to N decode
        tokens of prefill delay and each granted chunk displaces
        nothing (the chunk runs at the boundary, outside the macro
        launch) — the deferral bound is a boundary count, exactly as
        the deadline gate's estimates are per-launch
        (``decode_ema_s`` tracks one macro launch there).

        In-program inner loop (r22): a grant now schedules up to N of
        the slot's CHAINED chunks inside the macro launch itself — the
        decode batch keeps decoding through the same iterations, so
        preempting the chunk no longer protects interactive TPOT from
        a launch stall; it only bounds the launch's extra chunk work.
        The deadline gate mirrors this by charging ceil(chunks/N)
        whole launches at ``decode_ema_s`` (in-program units) instead
        of per-chunk boundary wall time."""
        if not partial:
            return None
        ranked = sorted(partial, key=lambda sr: (
            -self.effective_priority(sr[1], now),
            getattr(sr[1], "deadline_t", None)
            if getattr(sr[1], "deadline_t", None) is not None
            else float("inf"),
            sr[1].stats.submit_t))
        slot, req = ranked[0]
        if not decoding:
            req.chunk_deferrals = 0
            return slot
        top_decode = max(self.effective_priority(r, now)
                         for r in decoding)
        if self.effective_priority(req, now) >= top_decode:
            req.chunk_deferrals = 0
            return slot
        req.chunk_deferrals += 1
        if req.chunk_deferrals > self.cfg.max_chunk_deferrals:
            req.chunk_deferrals = 0
            return slot
        return None

    def shed(self, queue: List, now: float) -> List:
        if self.cfg.shed_after_s is None:
            return []
        limit = self.cfg.shed_after_s
        return [r for r in queue
                if now - r.stats.submit_t > limit]
