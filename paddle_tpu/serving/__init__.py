"""paddle_tpu.serving — production serving layer over the decode engine.

Turns `inference.ContinuousBatchingEngine` (paged KV + fixed-slot
continuous batching, PR r6) into a servable system:

- ``server``: threaded socket front-end with a newline-JSON protocol,
  request lifecycle (queued → prefill → decoding → done/evicted),
  incremental token streaming, health/stats endpoints, and graceful
  drain (stop admitting, finish in-flight, return pages,
  ``check_no_leak``).
- ``scheduler``: SLO-aware admission replacing head-of-queue FIFO —
  priority classes, max-queue-delay promotion, overload shedding with
  a typed ``ServerOverloaded`` reply, and bounded fairness so long
  prompts can't starve short ones (and vice versa).
- ``prefix_cache``: refcounted sharing of immutable FULL KV pages
  keyed by a rolling hash of their token block; admission reuses
  cached pages for matching prompt prefixes (skipping that prefill
  compute entirely), divergence is handled by writing the suffix into
  fresh private pages (shared pages are never mutated), LRU eviction
  fires only at refcount 0, and greedy outputs stay bit-identical to
  the uncached path (tests/test_serving.py). Hierarchical spill tiers
  (r15): with ``spill_bytes``/``spill_dir`` configured, evicted pages
  survive as crc32-checked host-RAM/disk blobs and a later hit
  restores them via one device_put + page-table splice instead of a
  re-prefill; the failover router steers keyed requests to the
  replica advertising their prefix (tests/test_prefix_tiers.py).
- ``metrics``: per-request TTFT / TPOT / queue-delay histograms and
  cache-hit / shed counters in core.monitor's StatRegistry, with a
  Prometheus-style text export — plus speculative-decoding
  acceptance-rate and tokens-per-step histograms (r8), engine
  occupancy gauges and resurrection/replay counters (r9).
- ``tracing``: end-to-end request tracing (r16) — a sampling,
  bounded-memory span tracer threading ONE trace id from the failover
  router through replica, scheduler queue, admission, every prefill
  chunk, decode/verify step, spill-tier restore, resurrection replay
  and failover hop; per-request span trees export as JSON (validated
  by tools/trace_lint.py) or Chrome trace events mergeable with
  ``jax.profiler`` device traces (tools/merge_traces.py). Off by
  default at ~zero hot-path cost; PT_SERVING_DEBUG=1 is this tracer
  at sample 1.0 with a stderr sink.
- ``fleet_metrics``: the fleet telemetry plane (r17) — the
  supervisor's probe cycle scrapes each replica's STRUCTURED metrics
  export (``ServingMetrics.export()``: exact counters, bucket-exact
  histogram counts, SLO window counts) and merges them bucket-exactly
  into fleet rollups with interpolated fleet quantiles; a live
  per-class SLO-attainment monitor (``--slo-ttft-ms``/
  ``--slo-tpot-ms``) with queue/debt pressure signals and a
  hysteretic ``scale_up``/``steady``/``scale_down`` verdict (the
  ROADMAP 3(a) autoscaler input, telemetry-only); MAD-based
  per-replica outlier detection; and a crash flight recorder
  (``--flight-dir``) writing atomic, byte-budget-ringed black-box
  bundles on resurrection/EngineFailed/stall
  (tools/flight_inspect.py lints them). Router ops ``fleet_stats`` /
  ``fleet_metrics`` expose it all on one port.
- ``supervisor``: crash-safe serving above the process boundary (r9)
  — N supervised replica processes with health-probed backoff
  restarts, fronted by a failover router that resubmits idempotent
  (keyed) requests from a dead replica to a live one. Below the
  process boundary, the server resurrects a persistently-failing
  engine and REPLAYS in-flight requests from their token history
  (greedy continuations bit-identical to the uninterrupted run), and
  a per-request ``deadline_ms`` budget is enforced at every lifecycle
  stage with typed ``DeadlineExceeded`` replies. The seeded chaos
  harness driving all of it lives in tools/chaos_serving.py.

Speculative decoding (r8): pass ``--speculate K`` (CLI) or
``speculative=SpeculativeConfig(k=K, draft=...)`` (engine kwargs) to
decode via draft-and-verify — greedy outputs stay bit-identical to
the vanilla engine while accepted drafts amortize the per-token
weight/KV stream (inference/speculative.py).

Reference analog: the framework's standalone inference engine + C
serving API (SURVEY §1 rows 7/12), reproduced TPU-natively as a Python
serving subsystem over one jitted decode step rather than a C ABI.
Paper basis: *Ragged Paged Attention* (PAPERS.md) — page-granular KV
management is what makes cross-request prefix sharing possible.
"""

from .fleet_metrics import (FleetMetrics, FlightRecorder,  # noqa: F401
                            PressureMonitor)
from .metrics import (Histogram, ServingMetrics,  # noqa: F401
                      SLOAttainment, merge_exports,
                      quantile_from_buckets)
from .prefix_cache import (DiskSpillTier, HostSpillTier,  # noqa: F401
                           PrefixCache, SpillCorrupt)
from .scheduler import (Priority, ServerOverloaded, SLOConfig,  # noqa: F401
                        SLOScheduler)
from .tracing import (RequestTrace, SpanTracer,  # noqa: F401
                      request_latencies, stderr_span_sink)


def __getattr__(name):
    # server.py / supervisor.py are lazy so `python -m
    # paddle_tpu.serving.<mod>` does not execute the module twice
    # (runpy re-runs what the package __init__ already imported)
    if name in ("ServingServer", "client_request", "PageFetchFailed",
                "fetch_page_blobs"):
        from . import server
        return getattr(server, name)
    if name in ("Supervisor", "FailoverRouter", "Replica"):
        from . import supervisor
        return getattr(supervisor, name)
    raise AttributeError(
        f"module 'paddle_tpu.serving' has no attribute {name!r}")
