"""Per-request serving observability.

Aggregates the engine's `RequestStats` records (admit time, prefill
ms, first-token time, tokens emitted — continuous_batching.py) into
TTFT / TPOT / queue-delay histograms plus cache-hit and shed counters,
and exports both as a Prometheus-style text page. Counters live in
core/monitor.py's process-global ``StatRegistry`` (the reference's
StatValue/StatRegistry monitor), so any other subsystem's stats ride
the same export.

This is the fix for the "which number is the framework" ambiguity
(VERDICT weak #5) at per-request granularity: TTFT (submit → first
token, queueing included) and TPOT (steady decode cadence) are
separate distributions instead of one blended wall-clock figure.
"""

from __future__ import annotations

import random
import threading
from bisect import bisect_left
from typing import Dict, List, Optional, Sequence

from ..core.monitor import GLOBAL_STATS, StatRegistry

__all__ = ["Histogram", "ServingMetrics"]

# log-ish spaced latency buckets (ms): sub-ms CPU-smoke prefills up to
# multi-second chip TTFTs land in distinct buckets
DEFAULT_MS_BUCKETS = (0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100,
                      250, 500, 1000, 2500, 5000, 10000)

# speculative-decoding distributions: acceptance rate is a ratio in
# [0, 1]; tokens-per-step lives in [1, k+1] (1 = speculation bought
# nothing, k+1 = every draft accepted)
RATIO_BUCKETS = (0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9,
                 0.95, 1.0)
TOKENS_PER_STEP_BUCKETS = (1.0, 1.25, 1.5, 2.0, 2.5, 3.0, 4.0, 5.0,
                           6.0, 8.0, 12.0, 16.0)

# chunked prefill (r11): per-request prefill launch counts. 1 = whole
# prefill (or a prompt that fits one chunk); an 8k prompt at a
# 256-token chunk lands at 32.
CHUNK_COUNT_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0,
                       24.0, 32.0, 48.0, 64.0)


class Histogram:
    """Fixed-bucket latency histogram with quantiles over a bounded
    uniform RESERVOIR of all observations (replace-with-probability
    n/i, so late traffic keeps entering the sample and quantiles track
    a live regression instead of freezing on warm-up-era values); the
    buckets stay exact forever."""

    def __init__(self, name: str,
                 buckets: Sequence[float] = DEFAULT_MS_BUCKETS,
                 max_samples: int = 65536):
        self.name = name
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self.total = 0
        self.sum = 0.0
        self._samples: List[float] = []
        self._max_samples = int(max_samples)
        self._resv_rng = random.Random(0)  # deterministic reservoir
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self.counts[bisect_left(self.buckets, v)] += 1
            self.total += 1
            self.sum += v
            if len(self._samples) < self._max_samples:
                self._samples.append(v)
            else:
                j = self._resv_rng.randrange(self.total)
                if j < self._max_samples:
                    self._samples[j] = v

    def percentile(self, p: float) -> Optional[float]:
        """Exact percentile over the retained samples (None if empty)."""
        with self._lock:
            if not self._samples:
                return None
            s = sorted(self._samples)
            idx = min(len(s) - 1, max(0, round(p / 100 * (len(s) - 1))))
            return s[idx]

    def snapshot(self) -> Dict[str, Optional[float]]:
        with self._lock:
            n = self.total
            mean = self.sum / n if n else None
        return {"count": n, "mean": mean,
                "p50": self.percentile(50), "p90": self.percentile(90),
                "p99": self.percentile(99)}

    def prometheus_lines(self) -> List[str]:
        """Cumulative-bucket text exposition (histogram type)."""
        name = self.name.replace(".", "_")
        out = [f"# TYPE {name} histogram"]
        with self._lock:
            acc = 0
            for le, c in zip(self.buckets, self.counts):
                acc += c
                out.append(f'{name}_bucket{{le="{le:g}"}} {acc}')
            acc += self.counts[-1]
            out.append(f'{name}_bucket{{le="+Inf"}} {acc}')
            out.append(f"{name}_sum {self.sum:g}")
            out.append(f"{name}_count {self.total}")
        return out


class ServingMetrics:
    """The serving layer's stat surface.

    ``observe_request`` consumes a finished `DecodeRequest` (any
    terminal state) from the engine's ``on_complete`` hook; counters
    land in the shared StatRegistry under ``serving.*`` names so
    ``GLOBAL_STATS.snapshot()`` sees them too."""

    COUNTERS = ("requests_total", "tokens_generated_total",
                "cache_hit_pages_total", "cache_miss_pages_total",
                "cache_hit_requests_total", "shed_total",
                "rejected_total", "evicted_total", "failed_total",
                "prefill_retries_total", "engine_errors_total",
                "spec_drafted_total", "spec_accepted_total",
                # crash-safe serving (r9): resurrection + typed-evict
                # accounting
                "engine_restarts_total", "replayed_requests_total",
                "engine_teardown_leaks_total",
                "engine_resurrect_failures_total",
                "deadline_exceeded_total", "stalled_total",
                "net_recv_drops_total",
                # chunked prefill (r11): prefill launches across every
                # terminal state (a deadline-evicted half-prefill's
                # chunks were still compute spent). NOT named
                # prefill_chunks_total: OpenMetrics reserves the
                # _total suffix for counter families, which would
                # collide with the serving_prefill_chunks HISTOGRAM
                # family on strict parsers.
                "prefill_chunk_launches_total",
                # hierarchical prefix cache (r15): per-tier hit split —
                # cache_hit_pages_total stays TOTAL reuse (device +
                # restored), these break out the spill-tier share —
                # plus the typed corrupt-blob fallback count
                "cache_host_hit_pages_total",
                "cache_disk_hit_pages_total",
                "cache_restored_pages_total",
                "cache_restore_corrupt_total",
                # end-to-end tracing (r16): sampling/ring accounting —
                # synced from the SpanTracer's lifetime counters at
                # scrape time (tracer counts are monotonic, so the
                # counter contract holds)
                "traces_sampled_total", "traces_finished_total",
                "trace_spans_dropped_total")

    def __init__(self, registry: Optional[StatRegistry] = None,
                 prefix: str = "serving"):
        self.registry = registry if registry is not None else GLOBAL_STATS
        self.prefix = prefix
        # live gauge source (engine occupancy): a callable returning
        # {name: value}, sampled at scrape time — the server wires
        # in-flight slots / free vs reserved pages / prefix-cache
        # residency through this
        self._gauge_fn = None
        self.ttft_ms = Histogram(f"{prefix}.ttft_ms")
        self.tpot_ms = Histogram(f"{prefix}.tpot_ms")
        self.queue_delay_ms = Histogram(f"{prefix}.queue_delay_ms")
        self.prefill_ms = Histogram(f"{prefix}.prefill_ms")
        self.e2e_ms = Histogram(f"{prefix}.e2e_ms")
        # speculative decoding: per-request acceptance rate and decode
        # tokens per verify step (both ride the Prometheus export)
        self.spec_accept_rate = Histogram(
            f"{prefix}.spec_accept_rate", buckets=RATIO_BUCKETS)
        self.spec_tokens_per_step = Histogram(
            f"{prefix}.spec_tokens_per_step",
            buckets=TOKENS_PER_STEP_BUCKETS)
        # chunked prefill (r11): launches per request and per-chunk
        # latency (total prefill_ms / chunks — the fixed chunk bucket
        # makes the mean representative)
        self.prefill_chunks = Histogram(
            f"{prefix}.prefill_chunks", buckets=CHUNK_COUNT_BUCKETS)
        self.prefill_chunk_ms = Histogram(
            f"{prefix}.prefill_chunk_ms")
        # hierarchical prefix cache (r15): wall time of the spill-tier
        # restore at admission (device_put + page-table splice) — the
        # number that must sit well under the prefill it replaces
        self.restore_ms = Histogram(f"{prefix}.restore_ms")
        # step timeline (r16): whole-engine-step wall time, fed from
        # the engine's ring-buffer deltas at scrape time (the server
        # tracks which steps it has already observed)
        self.step_ms = Histogram(f"{prefix}.step_ms")

    def counter(self, name: str):
        return self.registry.get(f"{self.prefix}.{name}")

    def reset(self) -> None:
        """Zero the serving counters (tests); histograms are rebuilt."""
        for c in self.COUNTERS:
            self.counter(c).reset()
        for h in ("ttft_ms", "tpot_ms", "queue_delay_ms", "prefill_ms",
                  "e2e_ms"):
            setattr(self, h, Histogram(f"{self.prefix}.{h}"))
        self.spec_accept_rate = Histogram(
            f"{self.prefix}.spec_accept_rate", buckets=RATIO_BUCKETS)
        self.spec_tokens_per_step = Histogram(
            f"{self.prefix}.spec_tokens_per_step",
            buckets=TOKENS_PER_STEP_BUCKETS)
        self.prefill_chunks = Histogram(
            f"{self.prefix}.prefill_chunks",
            buckets=CHUNK_COUNT_BUCKETS)
        self.prefill_chunk_ms = Histogram(
            f"{self.prefix}.prefill_chunk_ms")
        self.restore_ms = Histogram(f"{self.prefix}.restore_ms")
        self.step_ms = Histogram(f"{self.prefix}.step_ms")

    # -- ingestion ---------------------------------------------------------

    def set_gauge_fn(self, fn) -> None:
        """Install the occupancy-gauge source (None disables)."""
        self._gauge_fn = fn

    def gauges(self) -> Dict[str, float]:
        """Sample the gauge source (empty when unset or failing — a
        scrape must never die because the engine is mid-swap)."""
        if self._gauge_fn is None:
            return {}
        try:
            return {str(k): float(v)
                    for k, v in self._gauge_fn().items()}
        except Exception:
            return {}

    def observe_request(self, req) -> None:
        """Terminal-state hook (engine ``on_complete``)."""
        st = req.stats
        self.counter("requests_total").add()
        if st.prefill_chunks:
            # counted for EVERY terminal state: chunks launched for a
            # later-evicted request were still compute spent (the
            # chunk histograms below stay done-requests-only so they
            # describe complete prefills)
            self.counter("prefill_chunk_launches_total").add(
                st.prefill_chunks)
        if st.restored_pages or st.restore_corrupt:
            # spill-tier restore work happened at admission, so it is
            # counted for every terminal state too (r15)
            self.counter("cache_restored_pages_total").add(
                st.restored_pages)
            if st.restored_host_pages:
                self.counter("cache_host_hit_pages_total").add(
                    st.restored_host_pages)
            if st.restored_disk_pages:
                self.counter("cache_disk_hit_pages_total").add(
                    st.restored_disk_pages)
            if st.restore_corrupt:
                self.counter("cache_restore_corrupt_total").add(
                    st.restore_corrupt)
            if st.restore_ms:
                self.restore_ms.observe(st.restore_ms)
        if req.state == "shed":
            self.counter("shed_total").add()
            return
        if req.state == "evicted":
            self.counter("evicted_total").add()
            return
        if req.state == "deadline":
            self.counter("deadline_exceeded_total").add()
            # streamed tokens delivered before expiry still count
            self.counter("tokens_generated_total").add(st.tokens_out)
            return
        if req.state == "stalled":
            self.counter("stalled_total").add()
            self.counter("tokens_generated_total").add(st.tokens_out)
            return
        if req.state == "failed":
            self.counter("failed_total").add()
            if st.prefill_attempts:
                self.counter("prefill_retries_total").add(
                    st.prefill_attempts - 1)
            return
        self.counter("tokens_generated_total").add(st.tokens_out)
        if st.cache_enabled:
            # hit/miss accounting only when a prefix cache exists — a
            # cache-less deployment must not read as a 0%-hit cache
            if st.cached_pages:
                self.counter("cache_hit_requests_total").add()
                self.counter("cache_hit_pages_total").add(
                    st.cached_pages)
            self.counter("cache_miss_pages_total").add(
                max(0, st.prompt_pages - st.cached_pages))
        if st.prefill_attempts > 1:
            self.counter("prefill_retries_total").add(
                st.prefill_attempts - 1)
        if st.ttft_s is not None:
            self.ttft_ms.observe(st.ttft_s * 1e3)
        if st.tpot_s is not None:
            self.tpot_ms.observe(st.tpot_s * 1e3)
        if st.queue_delay_s is not None:
            self.queue_delay_ms.observe(st.queue_delay_s * 1e3)
        if st.prefill_ms:
            self.prefill_ms.observe(st.prefill_ms)
        if st.prefill_chunks:
            self.prefill_chunks.observe(st.prefill_chunks)
            if st.prefill_ms:
                self.prefill_chunk_ms.observe(
                    st.prefill_ms / st.prefill_chunks)
        if st.finish_t and st.submit_t:
            self.e2e_ms.observe((st.finish_t - st.submit_t) * 1e3)
        if st.spec_steps:
            self.counter("spec_drafted_total").add(st.spec_drafted)
            self.counter("spec_accepted_total").add(st.spec_accepted)
            if st.acceptance_rate is not None:
                self.spec_accept_rate.observe(st.acceptance_rate)
            if st.tokens_per_step is not None:
                self.spec_tokens_per_step.observe(st.tokens_per_step)

    # -- export ------------------------------------------------------------

    def snapshot(self) -> Dict:
        counters = {c: self.counter(c).get() for c in self.COUNTERS}
        return {
            "counters": counters,
            "gauges": self.gauges(),
            "ttft_ms": self.ttft_ms.snapshot(),
            "tpot_ms": self.tpot_ms.snapshot(),
            "queue_delay_ms": self.queue_delay_ms.snapshot(),
            "prefill_ms": self.prefill_ms.snapshot(),
            "e2e_ms": self.e2e_ms.snapshot(),
            "spec_accept_rate": self.spec_accept_rate.snapshot(),
            "spec_tokens_per_step":
                self.spec_tokens_per_step.snapshot(),
            "prefill_chunks": self.prefill_chunks.snapshot(),
            "prefill_chunk_ms": self.prefill_chunk_ms.snapshot(),
            "restore_ms": self.restore_ms.snapshot(),
            "step_ms": self.step_ms.snapshot(),
        }

    def prometheus_text(self) -> str:
        """Prometheus text exposition: serving histograms + every
        counter in the shared registry (``.`` → ``_``)."""
        # materialize the declared counters so a FRESH server exports
        # them at 0 (Prometheus convention: absent-until-first-event
        # counters break rate() and alerting on the scrape side)
        for c in self.COUNTERS:
            self.counter(c)
        lines: List[str] = []
        for h in (self.ttft_ms, self.tpot_ms, self.queue_delay_ms,
                  self.prefill_ms, self.e2e_ms, self.spec_accept_rate,
                  self.spec_tokens_per_step, self.prefill_chunks,
                  self.prefill_chunk_ms, self.restore_ms,
                  self.step_ms):
            lines.extend(h.prometheus_lines())
        for name, val in sorted(self.gauges().items()):
            gname = f"{self.prefix}_{name}".replace(".", "_")
            lines.append(f"# TYPE {gname} gauge")
            lines.append(f"{gname} {val:g}")
        for name, val in sorted(self.registry.snapshot().items()):
            pname = name.replace(".", "_")
            lines.append(f"# TYPE {pname} counter")
            lines.append(f"{pname} {val}")
        return "\n".join(lines) + "\n"
