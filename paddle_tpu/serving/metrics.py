"""Per-request serving observability.

Aggregates the engine's `RequestStats` records (admit time, prefill
ms, first-token time, tokens emitted — continuous_batching.py) into
TTFT / TPOT / queue-delay histograms plus cache-hit and shed counters,
and exports both as a Prometheus-style text page. Counters live in
core/monitor.py's process-global ``StatRegistry`` (the reference's
StatValue/StatRegistry monitor), so any other subsystem's stats ride
the same export.

This is the fix for the "which number is the framework" ambiguity
(VERDICT weak #5) at per-request granularity: TTFT (submit → first
token, queueing included) and TPOT (steady decode cadence) are
separate distributions instead of one blended wall-clock figure.
"""

from __future__ import annotations

import random
import threading
import time
from bisect import bisect_left
from collections import deque
from typing import Dict, List, Optional, Sequence

from ..core.monitor import GLOBAL_STATS, StatRegistry

__all__ = ["Histogram", "ServingMetrics", "SLOAttainment",
           "merge_exports", "quantile_from_buckets", "export_snapshot",
           "attainment_from_export"]

# log-ish spaced latency buckets (ms): sub-ms CPU-smoke prefills up to
# multi-second chip TTFTs land in distinct buckets
DEFAULT_MS_BUCKETS = (0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100,
                      250, 500, 1000, 2500, 5000, 10000)

# speculative-decoding distributions: acceptance rate is a ratio in
# [0, 1]; tokens-per-step lives in [1, k+1] (1 = speculation bought
# nothing, k+1 = every draft accepted)
RATIO_BUCKETS = (0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9,
                 0.95, 1.0)
TOKENS_PER_STEP_BUCKETS = (1.0, 1.25, 1.5, 2.0, 2.5, 3.0, 4.0, 5.0,
                           6.0, 8.0, 12.0, 16.0)

# chunked prefill (r11): per-request prefill launch counts. 1 = whole
# prefill (or a prompt that fits one chunk); an 8k prompt at a
# 256-token chunk lands at 32.
CHUNK_COUNT_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0,
                       24.0, 32.0, 48.0, 64.0)

# memory observatory (r18): per-request peak private page holdings —
# page-count scale (a 64-page request at page 64 is a 4k-token context)
PAGE_COUNT_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
                      256.0, 512.0)

# multi-step decode (r19): decode steps executed per macro launch —
# lives in [1, multi_step]; below-N buckets show early EOS exits
STEPS_PER_LAUNCH_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0,
                            24.0, 32.0, 48.0, 64.0)


class Histogram:
    """Fixed-bucket latency histogram with quantiles over a bounded
    uniform RESERVOIR of all observations (replace-with-probability
    n/i, so late traffic keeps entering the sample and quantiles track
    a live regression instead of freezing on warm-up-era values); the
    buckets stay exact forever."""

    def __init__(self, name: str,
                 buckets: Sequence[float] = DEFAULT_MS_BUCKETS,
                 max_samples: int = 65536):
        self.name = name
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self.total = 0
        self.sum = 0.0
        self._samples: List[float] = []
        self._max_samples = int(max_samples)
        self._resv_rng = random.Random(0)  # deterministic reservoir
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self.counts[bisect_left(self.buckets, v)] += 1
            self.total += 1
            self.sum += v
            if len(self._samples) < self._max_samples:
                self._samples.append(v)
            else:
                j = self._resv_rng.randrange(self.total)
                if j < self._max_samples:
                    self._samples[j] = v

    def percentile(self, p: float) -> Optional[float]:
        """Exact percentile over the retained samples (None if empty)."""
        with self._lock:
            if not self._samples:
                return None
            s = sorted(self._samples)
            idx = min(len(s) - 1, max(0, round(p / 100 * (len(s) - 1))))
            return s[idx]

    def snapshot(self) -> Dict[str, Optional[float]]:
        with self._lock:
            n = self.total
            mean = self.sum / n if n else None
        return {"count": n, "mean": mean,
                "p50": self.percentile(50), "p90": self.percentile(90),
                "p99": self.percentile(99)}

    def prometheus_lines(self) -> List[str]:
        """Cumulative-bucket text exposition (histogram type)."""
        name = self.name.replace(".", "_")
        out = [f"# TYPE {name} histogram"]
        with self._lock:
            acc = 0
            for le, c in zip(self.buckets, self.counts):
                acc += c
                out.append(f'{name}_bucket{{le="{le:g}"}} {acc}')
            acc += self.counts[-1]
            out.append(f'{name}_bucket{{le="+Inf"}} {acc}')
            out.append(f"{name}_sum {self.sum:g}")
            out.append(f"{name}_count {self.total}")
        return out

    # -- fleet telemetry (r17) ---------------------------------------------

    def export(self) -> Dict:
        """Wire-friendly exact state: per-bucket (NON-cumulative)
        counts with the last slot the +Inf overflow, plus sum/total.
        The fixed ladder makes replica exports MERGEABLE bucket-exactly
        (``merge_exports``); the reservoir is deliberately excluded —
        samples don't merge, fleet quantiles come from the buckets."""
        with self._lock:
            return {"name": self.name.replace(".", "_"),
                    "buckets": list(self.buckets),
                    "counts": list(self.counts),
                    "sum": self.sum, "total": self.total}


def merge_exports(exports: Sequence[Dict]) -> Dict:
    """Fold N ``Histogram.export()`` dicts (same bucket ladder) into
    one bucket-exact fleet export: counts sum element-wise, sum/total
    add. The merged ``_count``/``_sum``/``_bucket`` therefore equal
    the sums of the replica exports exactly — the fleet-rollup
    invariant the tests pin. Raises ValueError on a ladder mismatch
    (merging histograms measured in different buckets would silently
    misattribute mass)."""
    exports = [e for e in exports if e]
    if not exports:
        return {"name": "empty", "buckets": [], "counts": [0],
                "sum": 0.0, "total": 0}
    base = exports[0]
    buckets = list(base["buckets"])
    counts = [0] * (len(buckets) + 1)
    total, total_sum = 0, 0.0
    name = base.get("name", "merged")
    for e in exports:
        if list(e["buckets"]) != buckets:
            raise ValueError(
                f"bucket ladder mismatch merging {e.get('name')!r}: "
                f"{e['buckets']} != {buckets}")
        if len(e["counts"]) != len(counts):
            raise ValueError(
                f"count vector length {len(e['counts'])} != "
                f"{len(counts)} for {e.get('name')!r}")
        for i, c in enumerate(e["counts"]):
            counts[i] += int(c)
        total += int(e["total"])
        total_sum += float(e["sum"])
    return {"name": name, "buckets": buckets, "counts": counts,
            "sum": total_sum, "total": total}


def quantile_from_buckets(export: Dict, p: float) -> Optional[float]:
    """Interpolated quantile from an export's bucket counts (the
    prometheus ``histogram_quantile`` estimator): walk the cumulative
    counts to the target rank and interpolate linearly inside the
    containing bucket. The +Inf bucket clamps to the highest finite
    edge (there is no upper bound to interpolate toward). This is the
    FLEET quantile path — replica reservoirs don't merge, fixed
    buckets do — so it trades exactness for mergeability; on one
    replica it must land within a bucket width of the reservoir
    quantile (pinned by tests)."""
    total = int(export.get("total", 0))
    if total <= 0:
        return None
    buckets = export["buckets"]
    counts = export["counts"]
    target = (p / 100.0) * total
    acc = 0.0
    for i, c in enumerate(counts[:-1]):
        prev_acc = acc
        acc += c
        if acc >= target and c > 0:
            lo = buckets[i - 1] if i > 0 else 0.0
            hi = buckets[i]
            return lo + (hi - lo) * (target - prev_acc) / c
    # rank lands in the +Inf overflow bucket: no finite upper bound
    return float(buckets[-1]) if buckets else None


def export_snapshot(export: Dict) -> Dict[str, Optional[float]]:
    """Bucket-derived snapshot of an export (fleet rollups: same shape
    as ``Histogram.snapshot`` but quantiles interpolated, not
    reservoir-exact)."""
    n = int(export.get("total", 0))
    return {"count": n,
            "mean": (export["sum"] / n) if n else None,
            "p50": quantile_from_buckets(export, 50),
            "p90": quantile_from_buckets(export, 90),
            "p99": quantile_from_buckets(export, 99)}


# priority-int -> class-name mapping (serving/scheduler.py Priority);
# kept here as plain ints so metrics never imports the scheduler
_CLASS_NAMES = {0: "batch", 1: "normal", 2: "interactive"}


class SLOAttainment:
    """Live SLO-attainment tracker (r17 fleet telemetry): the rolling-
    window fraction of finished requests whose TTFT/TPOT met the
    configured targets, per priority class — computed ONLINE from the
    same lifecycle markers (submit/first-token/finish) the goodput
    bench reads from traces, so the live gauge and the trace-computed
    attainment must agree (the fleet_goodput bench pins ±0.05).

    Targets are optional (``None`` = that dimension always counts as
    met); ``window_s`` bounds memory AND recency — an autoscaler wants
    the last minute, not the process lifetime. ``observe`` runs on the
    engine thread inside ``observe_request``; export/attainment can run
    on scrape threads, hence the lock. Window entries are per finished
    request (one small tuple), pruned lazily at observe/read time.

    Merging: ``export()`` carries per-class (total, ttft_met,
    tpot_met, met) COUNTS over the window — counts sum across
    replicas, so the fleet attainment is exact over the union window
    (fleet_metrics.merge_slo_exports)."""

    def __init__(self, ttft_ms: Optional[float] = None,
                 tpot_ms: Optional[float] = None,
                 window_s: float = 120.0,
                 max_events: int = 65536):
        self.ttft_ms = None if ttft_ms is None else float(ttft_ms)
        self.tpot_ms = None if tpot_ms is None else float(tpot_ms)
        self.window_s = float(window_s)
        # (t, class_name, ttft_met, tpot_met) per finished request.
        # maxlen caps memory AND the export()-walk cost at sustained
        # high request rates (oldest events drop first — attainment
        # then covers the most recent max_events inside the window,
        # which is the recency an autoscaler wants anyway)
        self._events: "deque" = deque(maxlen=max(1, int(max_events)))
        self._lock = threading.Lock()

    @property
    def configured(self) -> bool:
        return self.ttft_ms is not None or self.tpot_ms is not None

    def set_targets(self, ttft_ms: Optional[float],
                    tpot_ms: Optional[float]) -> None:
        """Retarget at runtime (the server's ``slo`` op — calibration
        without a replica restart). Resets the window: attainment
        against old targets is not attainment against new ones."""
        with self._lock:
            self.ttft_ms = None if ttft_ms is None else float(ttft_ms)
            self.tpot_ms = None if tpot_ms is None else float(tpot_ms)
            self._events.clear()

    def _prune(self, now: float) -> None:
        horizon = now - self.window_s
        while self._events and self._events[0][0] < horizon:
            self._events.popleft()

    def observe(self, priority: int, ttft_s: Optional[float],
                tpot_s: Optional[float],
                now: Optional[float] = None) -> None:
        """One finished request's markers. A missing marker counts as
        MET for its dimension (a 1-token request has no TPOT; a
        request that produced no token never reaches here — terminal
        non-done states are not attainment inputs, matching the trace
        path which skips traces without lifecycle markers)."""
        now = time.monotonic() if now is None else now
        ttft_met = (self.ttft_ms is None or ttft_s is None
                    or ttft_s * 1e3 <= self.ttft_ms)
        tpot_met = (self.tpot_ms is None or tpot_s is None
                    or tpot_s * 1e3 <= self.tpot_ms)
        cls = _CLASS_NAMES.get(int(priority), "normal")
        with self._lock:
            self._events.append((now, cls, ttft_met, tpot_met))
            self._prune(now)

    def export(self, now: Optional[float] = None) -> Dict:
        """Wire form: per-class met/total counts over the window plus
        the targets (the fleet collector checks replicas agree)."""
        now = time.monotonic() if now is None else now
        classes: Dict[str, Dict[str, int]] = {}
        with self._lock:
            self._prune(now)
            for _t, cls, ttft_met, tpot_met in self._events:
                c = classes.setdefault(
                    cls, {"total": 0, "ttft_met": 0, "tpot_met": 0,
                          "met": 0})
                c["total"] += 1
                c["ttft_met"] += ttft_met
                c["tpot_met"] += tpot_met
                c["met"] += ttft_met and tpot_met
        return {"ttft_ms": self.ttft_ms, "tpot_ms": self.tpot_ms,
                "window_s": self.window_s, "classes": classes}

    def attainment(self) -> Dict[str, Optional[float]]:
        """Per-class attained fraction over the window (None = no
        finished requests in the window), plus an "all" rollup."""
        return attainment_from_export(self.export())


def attainment_from_export(slo_export: Dict
                           ) -> Dict[str, Optional[float]]:
    """Per-class + "all" attainment fractions from an ``SLOAttainment``
    export (replica-local or fleet-merged — counts are counts)."""
    out: Dict[str, Optional[float]] = {}
    tot = met = 0
    for cls, c in (slo_export.get("classes") or {}).items():
        out[cls] = (c["met"] / c["total"]) if c["total"] else None
        tot += c["total"]
        met += c["met"]
    out["all"] = (met / tot) if tot else None
    return out


class ServingMetrics:
    """The serving layer's stat surface.

    ``observe_request`` consumes a finished `DecodeRequest` (any
    terminal state) from the engine's ``on_complete`` hook; counters
    land in the shared StatRegistry under ``serving.*`` names so
    ``GLOBAL_STATS.snapshot()`` sees them too."""

    COUNTERS = ("requests_total", "tokens_generated_total",
                "cache_hit_pages_total", "cache_miss_pages_total",
                "cache_hit_requests_total", "shed_total",
                "rejected_total", "evicted_total", "failed_total",
                "prefill_retries_total", "engine_errors_total",
                "spec_drafted_total", "spec_accepted_total",
                # crash-safe serving (r9): resurrection + typed-evict
                # accounting
                "engine_restarts_total", "replayed_requests_total",
                "engine_teardown_leaks_total",
                "engine_resurrect_failures_total",
                "deadline_exceeded_total", "stalled_total",
                "net_recv_drops_total",
                # chunked prefill (r11): prefill launches across every
                # terminal state (a deadline-evicted half-prefill's
                # chunks were still compute spent). NOT named
                # prefill_chunks_total: OpenMetrics reserves the
                # _total suffix for counter families, which would
                # collide with the serving_prefill_chunks HISTOGRAM
                # family on strict parsers.
                "prefill_chunk_launches_total",
                # hierarchical prefix cache (r15): per-tier hit split —
                # cache_hit_pages_total stays TOTAL reuse (device +
                # restored), these break out the spill-tier share —
                # plus the typed corrupt-blob fallback count
                "cache_host_hit_pages_total",
                "cache_disk_hit_pages_total",
                "cache_restored_pages_total",
                "cache_restore_corrupt_total",
                # end-to-end tracing (r16): sampling/ring accounting —
                # synced from the SpanTracer's lifetime counters at
                # scrape time (tracer counts are monotonic, so the
                # counter contract holds)
                "traces_sampled_total", "traces_finished_total",
                "trace_spans_dropped_total",
                # multi-step decode (r19): macro launches — synced
                # from the engine's lifetime macro_launches counter at
                # scrape time (monotonic across resurrections is NOT
                # guaranteed engine-side, so the server accumulates)
                "macro_steps_total",
                # disaggregated serving (r20): cross-replica KV
                # handoff accounting — pages spliced from wire-fetched
                # blobs, bytes pulled over fetch_pages, and fetch
                # failures (each one a counted fall-back to local
                # prefill, never a hang)
                "handoff_pages_total", "handoff_bytes_total",
                "handoff_failures_total",
                # weight hot-swap (r24): swap outcomes — three flat
                # registry counters, rendered in the exposition as ONE
                # labeled weight_swaps_total{outcome=...} family — plus
                # cross-generation fetch/prefetch hints skipped typed
                # (a generation-mismatched peer page is never spliced)
                "weight_swaps_committed_total",
                "weight_swaps_rolled_back_total",
                "weight_swaps_failed_total",
                "cross_generation_skips_total")

    # outcome labels for the weight_swaps_total family; index-aligned
    # with the weight_swaps_*_total counters above
    SWAP_OUTCOMES = ("committed", "rolled_back", "failed")

    def __init__(self, registry: Optional[StatRegistry] = None,
                 prefix: str = "serving",
                 slo: Optional[SLOAttainment] = None):
        self.registry = registry if registry is not None else GLOBAL_STATS
        self.prefix = prefix
        # live SLO monitor (r17): always present so export()/the slo
        # op have a stable surface; without targets it tracks nothing
        # binding (every request counts as met) and exports no gauges
        self.slo = slo if slo is not None else SLOAttainment()
        # live gauge source (engine occupancy): a callable returning
        # {name: value}, sampled at scrape time — the server wires
        # in-flight slots / free vs reserved pages / prefix-cache
        # residency through this
        self._gauge_fn = None
        self.ttft_ms = Histogram(f"{prefix}.ttft_ms")
        self.tpot_ms = Histogram(f"{prefix}.tpot_ms")
        self.queue_delay_ms = Histogram(f"{prefix}.queue_delay_ms")
        self.prefill_ms = Histogram(f"{prefix}.prefill_ms")
        self.e2e_ms = Histogram(f"{prefix}.e2e_ms")
        # speculative decoding: per-request acceptance rate and decode
        # tokens per verify step (both ride the Prometheus export)
        self.spec_accept_rate = Histogram(
            f"{prefix}.spec_accept_rate", buckets=RATIO_BUCKETS)
        self.spec_tokens_per_step = Histogram(
            f"{prefix}.spec_tokens_per_step",
            buckets=TOKENS_PER_STEP_BUCKETS)
        # chunked prefill (r11): launches per request and per-chunk
        # latency (total prefill_ms / chunks — the fixed chunk bucket
        # makes the mean representative)
        self.prefill_chunks = Histogram(
            f"{prefix}.prefill_chunks", buckets=CHUNK_COUNT_BUCKETS)
        self.prefill_chunk_ms = Histogram(
            f"{prefix}.prefill_chunk_ms")
        # hierarchical prefix cache (r15): wall time of the spill-tier
        # restore at admission (device_put + page-table splice) — the
        # number that must sit well under the prefill it replaces
        self.restore_ms = Histogram(f"{prefix}.restore_ms")
        # step timeline (r16): whole-engine-step wall time, fed from
        # the engine's ring-buffer deltas at scrape time (the server
        # tracks which steps it has already observed)
        self.step_ms = Histogram(f"{prefix}.step_ms")
        # memory observatory (r18): per-request peak page attribution
        # from the engine's ledger-era RequestStats (every terminal
        # state that held pages contributes — an evicted request's
        # footprint was still capacity spent)
        self.request_peak_pages = Histogram(
            f"{prefix}.request_peak_pages", buckets=PAGE_COUNT_BUCKETS)
        # multi-step decode (r19): decode steps per macro launch
        # (early-EOS exits land under N) and host time spent BLOCKED
        # on a macro drain (0-ish = the overlap worked: the device
        # finished while the host ran the serving loop) — both fed
        # from step-timeline macro records at scrape time, like
        # step_ms
        self.steps_per_launch = Histogram(
            f"{prefix}.steps_per_launch",
            buckets=STEPS_PER_LAUNCH_BUCKETS)
        self.host_overlap_idle_ms = Histogram(
            f"{prefix}.host_overlap_idle_ms")
        # disaggregated serving (r20): wall time of the fetch_pages
        # RPC a decode replica's connection thread spent pulling a
        # request's chain from a peer (the number that must sit well
        # under the prefill it replaces, like restore_ms one wire hop
        # out)
        self.handoff_ms = Histogram(f"{prefix}.handoff_ms")
        # weight hot-swap (r24): wall time of the engine-side apply
        # (validate + set_state_dict + identity-cache refresh + cache
        # re-salt) — the pause a roll's clients actually feel
        self.swap_ms = Histogram(f"{prefix}.swap_ms")

    def counter(self, name: str):
        return self.registry.get(f"{self.prefix}.{name}")

    def reset(self) -> None:
        """Zero the serving counters (tests); histograms are rebuilt."""
        for c in self.COUNTERS:
            self.counter(c).reset()
        self.slo.set_targets(self.slo.ttft_ms, self.slo.tpot_ms)
        for h in ("ttft_ms", "tpot_ms", "queue_delay_ms", "prefill_ms",
                  "e2e_ms"):
            setattr(self, h, Histogram(f"{self.prefix}.{h}"))
        self.spec_accept_rate = Histogram(
            f"{self.prefix}.spec_accept_rate", buckets=RATIO_BUCKETS)
        self.spec_tokens_per_step = Histogram(
            f"{self.prefix}.spec_tokens_per_step",
            buckets=TOKENS_PER_STEP_BUCKETS)
        self.prefill_chunks = Histogram(
            f"{self.prefix}.prefill_chunks",
            buckets=CHUNK_COUNT_BUCKETS)
        self.prefill_chunk_ms = Histogram(
            f"{self.prefix}.prefill_chunk_ms")
        self.restore_ms = Histogram(f"{self.prefix}.restore_ms")
        self.step_ms = Histogram(f"{self.prefix}.step_ms")
        self.request_peak_pages = Histogram(
            f"{self.prefix}.request_peak_pages",
            buckets=PAGE_COUNT_BUCKETS)
        self.steps_per_launch = Histogram(
            f"{self.prefix}.steps_per_launch",
            buckets=STEPS_PER_LAUNCH_BUCKETS)
        self.host_overlap_idle_ms = Histogram(
            f"{self.prefix}.host_overlap_idle_ms")
        self.handoff_ms = Histogram(f"{self.prefix}.handoff_ms")
        self.swap_ms = Histogram(f"{self.prefix}.swap_ms")

    # -- ingestion ---------------------------------------------------------

    def set_gauge_fn(self, fn) -> None:
        """Install the occupancy-gauge source (None disables)."""
        self._gauge_fn = fn

    def gauges(self) -> Dict[str, float]:
        """Sample the gauge source (empty when unset or failing — a
        scrape must never die because the engine is mid-swap)."""
        if self._gauge_fn is None:
            return {}
        try:
            return {str(k): float(v)
                    for k, v in self._gauge_fn().items()}
        except Exception:
            return {}

    def observe_request(self, req) -> None:
        """Terminal-state hook (engine ``on_complete``)."""
        st = req.stats
        self.counter("requests_total").add()
        if st.prefill_chunks:
            # counted for EVERY terminal state: chunks launched for a
            # later-evicted request were still compute spent (the
            # chunk histograms below stay done-requests-only so they
            # describe complete prefills)
            self.counter("prefill_chunk_launches_total").add(
                st.prefill_chunks)
        if st.restored_pages or st.restore_corrupt:
            # spill-tier restore work happened at admission, so it is
            # counted for every terminal state too (r15)
            self.counter("cache_restored_pages_total").add(
                st.restored_pages)
            if st.restored_host_pages:
                self.counter("cache_host_hit_pages_total").add(
                    st.restored_host_pages)
            if st.restored_disk_pages:
                self.counter("cache_disk_hit_pages_total").add(
                    st.restored_disk_pages)
            if st.restore_corrupt:
                self.counter("cache_restore_corrupt_total").add(
                    st.restore_corrupt)
            if st.restore_ms:
                self.restore_ms.observe(st.restore_ms)
        if getattr(st, "peak_pages", 0):
            # any terminal state: pages held by a later-evicted
            # request were still pool capacity spent (r18)
            self.request_peak_pages.observe(st.peak_pages)
        if getattr(st, "handoff_pages", 0) or \
                getattr(st, "handoff_ms", 0.0):
            # disaggregated handoff (r20): counted for every terminal
            # state — the wire fetch and splice happened at admission,
            # like restore accounting (bytes/failures are counted by
            # the server at fetch time on the connection thread)
            self.counter("handoff_pages_total").add(st.handoff_pages)
            if st.handoff_ms:
                self.handoff_ms.observe(st.handoff_ms)
        if req.state == "shed":
            self.counter("shed_total").add()
            return
        if req.state == "evicted":
            self.counter("evicted_total").add()
            return
        if req.state == "deadline":
            self.counter("deadline_exceeded_total").add()
            # streamed tokens delivered before expiry still count
            self.counter("tokens_generated_total").add(st.tokens_out)
            return
        if req.state == "stalled":
            self.counter("stalled_total").add()
            self.counter("tokens_generated_total").add(st.tokens_out)
            return
        if req.state == "failed":
            self.counter("failed_total").add()
            if st.prefill_attempts:
                self.counter("prefill_retries_total").add(
                    st.prefill_attempts - 1)
            return
        self.counter("tokens_generated_total").add(st.tokens_out)
        if st.cache_enabled:
            # hit/miss accounting only when a prefix cache exists — a
            # cache-less deployment must not read as a 0%-hit cache
            if st.cached_pages:
                self.counter("cache_hit_requests_total").add()
                self.counter("cache_hit_pages_total").add(
                    st.cached_pages)
            self.counter("cache_miss_pages_total").add(
                max(0, st.prompt_pages - st.cached_pages))
        if st.prefill_attempts > 1:
            self.counter("prefill_retries_total").add(
                st.prefill_attempts - 1)
        if st.first_token_t:
            # live SLO monitor (r17): a DONE request that produced a
            # first token is an attainment input — the same lifecycle
            # markers the goodput bench reads from traces, evaluated
            # online against the configured targets
            self.slo.observe(getattr(req, "priority", 1),
                             st.ttft_s, st.tpot_s)
        if st.ttft_s is not None:
            self.ttft_ms.observe(st.ttft_s * 1e3)
        if st.tpot_s is not None:
            self.tpot_ms.observe(st.tpot_s * 1e3)
        if st.queue_delay_s is not None:
            self.queue_delay_ms.observe(st.queue_delay_s * 1e3)
        if st.prefill_ms:
            self.prefill_ms.observe(st.prefill_ms)
        if st.prefill_chunks:
            self.prefill_chunks.observe(st.prefill_chunks)
            if st.prefill_ms:
                self.prefill_chunk_ms.observe(
                    st.prefill_ms / st.prefill_chunks)
        if st.finish_t and st.submit_t:
            self.e2e_ms.observe((st.finish_t - st.submit_t) * 1e3)
        if st.spec_steps:
            self.counter("spec_drafted_total").add(st.spec_drafted)
            self.counter("spec_accepted_total").add(st.spec_accepted)
            if st.acceptance_rate is not None:
                self.spec_accept_rate.observe(st.acceptance_rate)
            if st.tokens_per_step is not None:
                self.spec_tokens_per_step.observe(st.tokens_per_step)

    # -- export ------------------------------------------------------------

    def snapshot(self) -> Dict:
        counters = {c: self.counter(c).get() for c in self.COUNTERS}
        return {
            "counters": counters,
            "gauges": self.gauges(),
            "ttft_ms": self.ttft_ms.snapshot(),
            "tpot_ms": self.tpot_ms.snapshot(),
            "queue_delay_ms": self.queue_delay_ms.snapshot(),
            "prefill_ms": self.prefill_ms.snapshot(),
            "e2e_ms": self.e2e_ms.snapshot(),
            "spec_accept_rate": self.spec_accept_rate.snapshot(),
            "spec_tokens_per_step":
                self.spec_tokens_per_step.snapshot(),
            "prefill_chunks": self.prefill_chunks.snapshot(),
            "prefill_chunk_ms": self.prefill_chunk_ms.snapshot(),
            "restore_ms": self.restore_ms.snapshot(),
            "step_ms": self.step_ms.snapshot(),
            "request_peak_pages": self.request_peak_pages.snapshot(),
            "handoff_ms": self.handoff_ms.snapshot(),
            "swap_ms": self.swap_ms.snapshot(),
            # live SLO monitor (r17): targets + rolling attainment
            "slo": {"ttft_ms": self.slo.ttft_ms,
                    "tpot_ms": self.slo.tpot_ms,
                    "attainment": self.slo.attainment()},
        }

    def _histograms(self) -> Dict[str, Histogram]:
        """Every histogram this surface owns, by attribute name — the
        one list export()/prometheus_text iterate so a histogram added
        later can't silently miss either surface."""
        return {"ttft_ms": self.ttft_ms, "tpot_ms": self.tpot_ms,
                "queue_delay_ms": self.queue_delay_ms,
                "prefill_ms": self.prefill_ms, "e2e_ms": self.e2e_ms,
                "spec_accept_rate": self.spec_accept_rate,
                "spec_tokens_per_step": self.spec_tokens_per_step,
                "prefill_chunks": self.prefill_chunks,
                "prefill_chunk_ms": self.prefill_chunk_ms,
                "restore_ms": self.restore_ms,
                "step_ms": self.step_ms,
                "request_peak_pages": self.request_peak_pages,
                "steps_per_launch": self.steps_per_launch,
                "host_overlap_idle_ms": self.host_overlap_idle_ms,
                "handoff_ms": self.handoff_ms,
                "swap_ms": self.swap_ms}

    def export(self) -> Dict:
        """Fleet-telemetry wire form (r17): exact counters, sampled
        gauges, every histogram's bucket-exact ``export()``, and the
        SLO monitor's window counts — everything the supervisor-side
        collector needs, structured, so the fleet plane never parses
        exposition text. Deliberately excludes reservoirs (don't
        merge) and traces (their own op)."""
        return {"v": 1, "t": time.time(),
                "prefix": self.prefix,
                "counters": {c: self.counter(c).get()
                             for c in self.COUNTERS},
                "gauges": self.gauges(),
                "histograms": {k: h.export()
                               for k, h in self._histograms().items()},
                "slo": self.slo.export()}

    def _slo_lines(self) -> List[str]:
        """``serving_slo_attainment{class=...}`` gauges (plus the
        targets) — only once targets are configured, so a deployment
        without SLOs doesn't export a meaningless 1.0."""
        if not self.slo.configured:
            return []
        lines = [f"# TYPE {self.prefix}_slo_attainment gauge"]
        att = self.slo.attainment()
        for cls in sorted(att):
            v = att[cls]
            if v is not None:
                lines.append(
                    f'{self.prefix}_slo_attainment{{class="{cls}"}} '
                    f"{v:g}")
        for dim, target in (("ttft", self.slo.ttft_ms),
                            ("tpot", self.slo.tpot_ms)):
            if target is not None:
                gname = f"{self.prefix}_slo_{dim}_target_ms"
                lines.append(f"# TYPE {gname} gauge")
                lines.append(f"{gname} {target:g}")
        return lines

    def _swap_lines(self) -> List[str]:
        """The ``weight_swaps_total{outcome=...}`` labeled family
        (r24): the three flat outcome counters rendered as one
        counter family; the raw per-outcome registry names are
        suppressed from the generic counter loop so strict parsers
        see exactly one family."""
        fam = f"{self.prefix}_weight_swaps_total"
        lines = [f"# TYPE {fam} counter"]
        for outcome in self.SWAP_OUTCOMES:
            v = self.counter(f"weight_swaps_{outcome}_total").get()
            lines.append(f'{fam}{{outcome="{outcome}"}} {v}')
        return lines

    def prometheus_text(self) -> str:
        """Prometheus text exposition: serving histograms + every
        counter in the shared registry (``.`` → ``_``)."""
        # materialize the declared counters so a FRESH server exports
        # them at 0 (Prometheus convention: absent-until-first-event
        # counters break rate() and alerting on the scrape side)
        for c in self.COUNTERS:
            self.counter(c)
        lines: List[str] = []
        for h in self._histograms().values():
            lines.extend(h.prometheus_lines())
        lines.extend(self._slo_lines())
        lines.extend(self._swap_lines())
        for name, val in sorted(self.gauges().items()):
            gname = f"{self.prefix}_{name}".replace(".", "_")
            lines.append(f"# TYPE {gname} gauge")
            lines.append(f"{gname} {val:g}")
        # the per-outcome swap counters are already exported above as
        # the labeled weight_swaps_total family
        labeled = {f"{self.prefix}.weight_swaps_{o}_total"
                   for o in self.SWAP_OUTCOMES}
        for name, val in sorted(self.registry.snapshot().items()):
            if name in labeled:
                continue
            pname = name.replace(".", "_")
            lines.append(f"# TYPE {pname} counter")
            lines.append(f"{pname} {val}")
        return "\n".join(lines) + "\n"
