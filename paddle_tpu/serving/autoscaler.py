"""Closed-loop autoscaling actuator for the serving fleet (r21).

ROADMAP item 1's missing half: the `PressureMonitor` (fleet_metrics,
r17/r18) publishes a hysteretic ``scale_up``/``steady``/``scale_down``
verdict and until now NOTHING consumed it. The `Autoscaler` here is
the consumer — a supervisor-side control loop that

- SPAWNS a replica on ``scale_up`` and DRAINS-THEN-KILLS one on
  ``scale_down``, bounded by a min/max-replica envelope with
  per-direction cooldowns and a single-action-in-flight rule;
- goes past replica COUNT to fleet SHAPE on disaggregated fleets: the
  README tuning rule ("grow the prefill side when handoff prefill
  failures climb, the decode side when TPOT attainment drops") made
  executable — a mixed/over-represented replica is RE-ROLED via
  drain + restart with a new ``--role`` instead of cold-spawning.

Robustness is the headline. Every scale action is journaled to an
atomic crc-checked fleet-state file (`FleetJournal`: tmp + rename +
fsync, the ResilientCheckpointManager discipline) BEFORE the process
action it describes, so a supervisor that dies mid-action leaves a
record a restarted supervisor can act on: `plan_recovery` +
`Autoscaler.recover` re-ADOPT running replicas found in the journal
(or by their ``PT_SUPERVISOR_JOURNAL`` env marker), reap or adopt an
orphaned half-spawn, resume or roll back a half-finished drain
(chains already handed to survivors stay valid; the victim is
re-drained or re-admitted), and never double-spawn. Mid-action
failures degrade typed and counted: a spawn that never goes ready is
killed and still charged against the cooldown; a drain-handoff
failure falls back to plain drain (the r20 re-prefill-on-first-use
contract); the router's replica set is updated only AFTER the
journal commit.

Chaos hook: ``PT_AUTOSCALE_HOLD_S`` sleeps inside every action's
journaled-but-uncommitted window so tools/chaos_serving.py (invariant
7) can SIGKILL the supervisor mid-spawn / mid-scale-down
deterministically. Zero-cost when unset.

Run it::

    python -m paddle_tpu.serving.supervisor --replicas 2 \
        --autoscale --min-replicas 1 --max-replicas 4 --cooldown-s 30
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["AutoscaleConfig", "FleetJournal", "Autoscaler",
           "load_journal", "plan_recovery", "scan_marked_replicas"]

_ROLES = ("mixed", "prefill", "decode")
# env markers _spawn stamps on every journal-managed replica: recovery
# (and the conftest stray guard) can attribute an orphaned server
# process to its fleet even when the journal's pid snapshot is stale
# (the monitor loop respawns crashed replicas without a journal write)
JOURNAL_ENV = "PT_SUPERVISOR_JOURNAL"
REPLICA_IDX_ENV = "PT_REPLICA_IDX"


def _canonical(body: Dict) -> bytes:
    """The byte form the journal crc covers: key-sorted, no
    whitespace — any reader (tools/flight_inspect.py recomputes this
    without importing paddle_tpu) derives the same digest."""
    return json.dumps(body, sort_keys=True,
                      separators=(",", ":")).encode()


def load_journal(path: str) -> Tuple[Optional[Dict], Optional[str]]:
    """Read + verify a fleet journal; returns ``(body, error)`` —
    exactly one is None. A missing file is not an error distinct from
    a torn one to the CALLER (both mean "no trusted state"), but the
    error string says which for the operator."""
    try:
        with open(path, encoding="utf-8") as f:
            obj = json.load(f)
    except FileNotFoundError:
        return None, f"{path}: no journal"
    except Exception as e:
        return None, f"{path}: unreadable ({type(e).__name__}: {e})"
    if not isinstance(obj, dict) or "body" not in obj:
        return None, f"{path}: not a journal object"
    body = obj["body"]
    crc = zlib.crc32(_canonical(body))
    if obj.get("crc") != crc:
        return None, (f"{path}: crc mismatch "
                      f"({obj.get('crc')} != {crc})")
    return body, None


class FleetJournal:
    """Atomic crc-checked fleet-state file.

    One JSON object ``{"v": 1, "crc": <crc32 of canonical body>,
    "body": {...}}`` rewritten WHOLE on every mutation (tmp + rename
    + fsync — the ResilientCheckpointManager discipline: a crash
    mid-write leaves the previous committed state, never a torn
    file). The body holds the action seq counter, the owning
    supervisor pid, the last COMMITTED fleet (idx/pid/port/role per
    replica), and an append-only action log: each action contributes
    a ``begin`` entry (written BEFORE the process action), optional
    ``launched`` (spawn pid known), and a terminal ``commit`` or
    ``rollback``. The log keeps a bounded tail but never drops an
    entry belonging to an unresolved seq."""

    MAX_ACTION_ENTRIES = 256

    def __init__(self, path: str,
                 supervisor_pid: Optional[int] = None):
        self.path = path
        self._lock = threading.Lock()
        self.writes_total = 0
        self.write_failures_total = 0
        self._body: Dict = {"seq": 0,
                            "supervisor_pid": (supervisor_pid
                                               or os.getpid()),
                            "fleet": [], "actions": []}

    # -- persistence -------------------------------------------------------

    def _write_locked(self) -> None:
        body = self._body
        obj = {"v": 1, "crc": zlib.crc32(_canonical(body)),
               "body": body}
        tmp = self.path + ".tmp"
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(obj, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            self.writes_total += 1
        except OSError:
            # a journal that cannot persist must not take the fleet
            # down; the failure is counted and surfaces in status()
            self.write_failures_total += 1

    def adopt_body(self, body: Dict) -> None:
        """Continue a recovered journal: keep its seq counter (action
        seqs stay monotonic ACROSS supervisor generations), its
        action log and its committed weight config; the fleet
        snapshot and owner pid are ours now."""
        with self._lock:
            self._body["seq"] = int(body.get("seq") or 0)
            self._body["actions"] = list(body.get("actions") or ())
            self._body["supervisor_pid"] = os.getpid()
            if isinstance(body.get("config"), dict):
                self._body["config"] = dict(body["config"])
            self._write_locked()

    # -- mutation ----------------------------------------------------------

    def _append_locked(self, entry: Dict) -> None:
        acts = self._body["actions"]
        acts.append(entry)
        if len(acts) > self.MAX_ACTION_ENTRIES:
            resolved = {e["seq"] for e in acts
                        if e.get("phase") in ("commit", "rollback")}
            keep = acts[-self.MAX_ACTION_ENTRIES:]
            head = [e for e in acts[:-self.MAX_ACTION_ENTRIES]
                    if e["seq"] not in resolved]
            self._body["actions"] = head + keep

    def begin(self, action: str, **fields) -> int:
        """Allocate the next action seq and journal the INTENT —
        called before the process action so a crash can only lose
        work the journal already names."""
        with self._lock:
            self._body["seq"] += 1
            seq = self._body["seq"]
            entry = {"seq": seq, "action": action, "phase": "begin",
                     "t_unix": time.time()}
            entry.update(fields)
            self._append_locked(entry)
            self._write_locked()
            return seq

    def update(self, seq: int, phase: str = "launched",
               **fields) -> None:
        with self._lock:
            entry = {"seq": seq, "phase": phase,
                     "t_unix": time.time()}
            entry.update(fields)
            self._append_locked(entry)
            self._write_locked()

    def commit(self, seq: int, **fields) -> None:
        self.update(seq, phase="commit", **fields)

    def rollback(self, seq: int, reason: str = "", **fields) -> None:
        self.update(seq, phase="rollback", reason=reason, **fields)

    def record_fleet(self, fleet: List[Dict]) -> None:
        """Persist the COMMITTED fleet (who exists, where). Also the
        monitor-respawn refresh path: pids change without a scale
        action, and recovery trusts this snapshot first."""
        with self._lock:
            self._body["fleet"] = list(fleet)
            self._write_locked()

    def record_config(self, checkpoint: Optional[str],
                      generation: int) -> None:
        """Persist the fleet's COMMITTED weight config (r24): the
        checkpoint directory and weight generation every respawn
        boots from. Written when a roll fully commits — recovery
        restores it so a restarted supervisor spawns dead replicas
        on the rolled weights, and an incomplete roll converges BACK
        to exactly this config."""
        with self._lock:
            self._body["config"] = {
                "checkpoint": checkpoint,
                "generation": int(generation)}
            self._write_locked()

    def config(self) -> Dict:
        with self._lock:
            return dict(self._body.get("config") or {})

    # -- reads -------------------------------------------------------------

    def tail(self, n: int = 16) -> List[Dict]:
        with self._lock:
            return [dict(e) for e in self._body["actions"][-n:]]

    def fleet(self) -> List[Dict]:
        with self._lock:
            return [dict(e) for e in self._body["fleet"]]

    @property
    def seq(self) -> int:
        with self._lock:
            return self._body["seq"]


def open_actions(body: Dict) -> List[Dict]:
    """Actions with a ``begin`` and no terminal ``commit``/
    ``rollback`` — merged per seq (later phases overlay fields, e.g.
    the spawn pid from ``launched``), oldest first."""
    merged: Dict[int, Dict] = {}
    resolved = set()
    for e in (body.get("actions") or ()):
        seq = e.get("seq")
        if not isinstance(seq, int):
            continue
        if e.get("phase") == "begin":
            merged[seq] = dict(e)
        elif e.get("phase") in ("commit", "rollback"):
            resolved.add(seq)
        elif seq in merged:
            upd = {k: v for k, v in e.items() if k != "phase"}
            merged[seq].update(upd)
    return [merged[s] for s in sorted(merged) if s not in resolved]


# ---------------------------------------------------------------------------
# orphan discovery + adoption plumbing
# ---------------------------------------------------------------------------


def _proc_environ(pid: int) -> Dict[str, str]:
    try:
        with open(f"/proc/{pid}/environ", "rb") as f:
            raw = f.read()
    except OSError:
        return {}
    out = {}
    for part in raw.split(b"\0"):
        if b"=" in part:
            k, _, v = part.partition(b"=")
            out[k.decode("utf-8", "replace")] = \
                v.decode("utf-8", "replace")
    return out


def _proc_cmdline(pid: int) -> str:
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            return f.read().replace(b"\0", b" ").decode(
                "utf-8", "replace")
    except OSError:
        return ""


def _pid_is_replica(pid: int, port: Optional[int] = None) -> bool:
    """Is ``pid`` a live serving-server process (optionally on
    ``port``)? The cmdline check is the pid-reuse guard: a recycled
    pid running something else must never be adopted or signalled."""
    cmd = _proc_cmdline(pid)
    if "paddle_tpu.serving.server" not in cmd:
        return False
    if port is not None and f"--port {port}" not in cmd:
        return False
    return True


def scan_marked_replicas(journal_path: str) -> Dict[int, Dict]:
    """Find every live server process stamped with OUR journal's env
    marker: ``{idx: {"pid": p, "port": q}}``. Catches replicas the
    journal's fleet snapshot missed (a monitor respawn between
    snapshot refreshes) — the never-strand backstop."""
    out: Dict[int, Dict] = {}
    try:
        pids = [int(p) for p in os.listdir("/proc") if p.isdigit()]
    except OSError:
        return out
    me = os.getpid()
    for pid in pids:
        if pid == me:
            continue
        cmd = _proc_cmdline(pid)
        if "paddle_tpu.serving.server" not in cmd:
            continue
        env = _proc_environ(pid)
        if env.get(JOURNAL_ENV) != journal_path:
            continue
        try:
            idx = int(env.get(REPLICA_IDX_ENV, ""))
        except ValueError:
            continue
        port = None
        toks = cmd.split()
        if "--port" in toks:
            try:
                port = int(toks[toks.index("--port") + 1])
            except (ValueError, IndexError):
                port = None
        out[idx] = {"pid": pid, "port": port}
    return out


class _AdoptedProc:
    """Popen-shaped handle over a replica ADOPTED from the journal:
    the process is not our child, so ``waitpid`` is unavailable —
    liveness is polled through /proc with the cmdline pid-reuse
    guard, signals go through ``os.kill``. Implements exactly the
    Popen surface the Supervisor uses (poll/wait/terminate/kill/
    send_signal/pid), so adopted and spawned replicas ride the same
    monitor/teardown code."""

    def __init__(self, pid: int, port: Optional[int] = None):
        self.pid = int(pid)
        self._port = port
        self.returncode: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self.returncode is None and \
                not _pid_is_replica(self.pid, self._port):
            self.returncode = 0  # exit status unknowable: not ours
        return self.returncode

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while self.poll() is None:
            if deadline is not None and \
                    time.monotonic() >= deadline:
                raise subprocess.TimeoutExpired(
                    f"adopted pid {self.pid}", timeout)
            time.sleep(0.05)
        return self.returncode

    def send_signal(self, sig: int) -> None:
        if self.poll() is not None:
            return
        try:
            os.kill(self.pid, sig)
        except OSError:
            pass

    def terminate(self) -> None:
        self.send_signal(signal.SIGTERM)

    def kill(self) -> None:
        self.send_signal(signal.SIGKILL)


# ---------------------------------------------------------------------------
# recovery planning (pure — unit-testable without processes)
# ---------------------------------------------------------------------------


def plan_recovery(body: Optional[Dict], scan: Dict[int, Dict],
                  min_replicas: int, max_replicas: int,
                  alive: Optional[Callable[[int, Optional[int]],
                                           bool]] = None) -> Dict:
    """Decide what a restarted supervisor does with the journal +
    the live-process scan. Pure function of its inputs (``alive``
    injectable for tests; defaults to the /proc check):

    - every journal-fleet replica still running is ADOPTED; a dead
      one is RESPAWNED (fresh process, same idx/role);
    - a scanned live replica the fleet snapshot missed is adopted
      too (monitor respawn raced the snapshot) — never stranded;
    - an open ``spawn`` is adopted + committed when its process runs
      and the envelope has room, else reaped + rolled back; a spawn
      that never launched is rolled back (nothing to reap);
    - an open ``drain`` whose victim is dead is committed (the kill
      half finished); a live victim is RESUMED (re-drained — chains
      already shipped to survivors stay valid) when the envelope
      allows the removal, else ROLLED BACK and the victim re-admitted
      as a full member;
    - an open ``rerole`` resumes against a live victim and completes
      as a respawn-with-new-role against a dead one;
    - an open ``roll`` (r24 weight upgrade) resumes FORWARD when the
      swap was confirmed (``swapped`` recorded) or any sibling roll
      action to the same target generation already committed (the
      canary proved the checkpoint) — the fleet converges onto the
      new generation; otherwise it converges BACK to the journal's
      committed weight config and the action rolls back
      (``roll_incomplete``). Either way the action stays OPEN until
      the executor finishes converging, so a second crash mid-resume
      resumes again instead of stranding a mixed-generation fleet.

    Adoption is keyed by replica idx, so the same process can never
    be adopted twice and a planned respawn never duplicates a live
    one — the never-double-spawn contract."""
    if alive is None:
        alive = _pid_is_replica
    plan = {"adopt": [], "respawn": [], "reap": [],
            "resolve": [], "resume": [], "errors": []}
    fleet = {e["idx"]: dict(e)
             for e in ((body or {}).get("fleet") or ())
             if isinstance(e, dict) and isinstance(e.get("idx"), int)}
    # scan overlays the snapshot: a respawn between snapshot
    # refreshes means the journal pid is stale but the scan is live
    for idx, info in scan.items():
        ent = fleet.setdefault(idx, {"idx": idx, "role": "mixed"})
        ent["pid"], ent["port"] = info["pid"], info.get("port")
    claimed: set = set()
    members: Dict[int, Dict] = {}

    def is_alive(ent: Dict) -> bool:
        pid = ent.get("pid")
        return isinstance(pid, int) and alive(pid, ent.get("port"))

    opens = open_actions(body) if body else []
    open_idxs = {a.get("replica") for a in opens}
    # roll recovery (r24): a target generation is PROVEN when any
    # roll action to it committed (the canary survived its window) —
    # an open sibling then resumes forward instead of rolling back
    roll_begins: Dict[int, Dict] = {}
    committed_seqs: set = set()
    for e in ((body or {}).get("actions") or ()):
        if not isinstance(e, dict):
            continue
        if e.get("phase") == "begin" and e.get("action") == "roll":
            roll_begins[e.get("seq")] = e
        elif e.get("phase") == "commit":
            committed_seqs.add(e.get("seq"))
    proven_gens = {e.get("generation_to")
                   for s, e in roll_begins.items()
                   if s in committed_seqs and not e.get("rollback")}
    for idx, ent in sorted(fleet.items()):
        if idx in open_idxs:
            continue  # the action resolution below owns this replica
        if is_alive(ent):
            plan["adopt"].append(ent)
            members[idx] = ent
        else:
            plan["respawn"].append({"idx": idx,
                                    "role": ent.get("role", "mixed")})
            members[idx] = ent
        claimed.add(idx)

    for act in opens:
        seq, kind = act["seq"], act.get("action")
        idx = act.get("replica")
        ent = fleet.get(idx, {"idx": idx,
                              "role": act.get("role", "mixed")})
        if act.get("pid") is not None:
            ent.setdefault("pid", act["pid"])
            ent.setdefault("port", act.get("port"))
        live_now = is_alive(ent)
        if kind == "spawn":
            if live_now and len(members) < max_replicas:
                ent.setdefault("role", act.get("role", "mixed"))
                plan["adopt"].append(ent)
                members[idx] = ent
                plan["resolve"].append(
                    (seq, "commit", "adopted_on_recovery"))
            elif live_now:
                plan["reap"].append(ent)
                plan["resolve"].append(
                    (seq, "rollback", "reaped_over_envelope"))
            else:
                plan["resolve"].append(
                    (seq, "rollback", "orphan_dead"))
        elif kind == "drain":
            survivors = len([m for m in members if m != idx])
            if not live_now:
                plan["resolve"].append(
                    (seq, "commit", "victim_already_dead"))
            elif survivors >= min_replicas and survivors >= 1:
                plan["adopt"].append(dict(ent, draining=True))
                plan["resume"].append({"seq": seq, "action": "drain",
                                       "replica": idx})
            else:
                # re-admit: killing it now would violate the envelope
                plan["adopt"].append(ent)
                members[idx] = ent
                plan["resolve"].append(
                    (seq, "rollback", "readmitted_below_min"))
        elif kind == "rerole":
            to_role = act.get("role_to", "mixed")
            if live_now:
                plan["adopt"].append(
                    dict(ent, role=act.get("role_from",
                                           ent.get("role", "mixed")),
                         draining=True))
                plan["resume"].append(
                    {"seq": seq, "action": "rerole", "replica": idx,
                     "role": to_role})
            else:
                plan["respawn"].append({"idx": idx, "role": to_role})
                members[idx] = dict(ent, role=to_role)
                plan["resolve"].append(
                    (seq, "commit", "respawned_with_new_role"))
        elif kind == "roll":
            # the victim is a normal fleet member either way (a swap
            # never removes a process); which GENERATION the fleet
            # converges to is the resume entry's job
            if live_now:
                plan["adopt"].append(ent)
            else:
                plan["respawn"].append(
                    {"idx": idx, "role": ent.get("role", "mixed")})
            members[idx] = ent
            gen_to = act.get("generation_to")
            if act.get("swapped") or gen_to in proven_gens:
                plan["resume"].append(
                    {"seq": seq, "action": "roll", "replica": idx,
                     "checkpoint": act.get("checkpoint"),
                     "generation": gen_to})
            else:
                cfg = (body or {}).get("config") or {}
                plan["resume"].append(
                    {"seq": seq, "action": "roll_back",
                     "replica": idx,
                     "checkpoint": cfg.get("checkpoint"),
                     "generation": int(
                         cfg.get("generation")
                         or act.get("generation_from") or 0)})
        else:
            plan["resolve"].append(
                (seq, "rollback", f"unknown_action_{kind}"))
    return plan


# ---------------------------------------------------------------------------
# the actuator
# ---------------------------------------------------------------------------


@dataclass
class AutoscaleConfig:
    """Envelope + pacing for the actuator. ``cooldown_up_s`` gates
    spawns, ``cooldown_down_s`` gates drains AND re-roles (both cost
    a drain); ``shape`` enables the prefill:decode ratio controller
    on disaggregated fleets."""

    min_replicas: int = 1
    max_replicas: int = 4
    cooldown_up_s: float = 30.0
    cooldown_down_s: float = 60.0
    interval_s: float = 1.0
    spawn_ready_timeout_s: float = 300.0
    drain_timeout_s: float = 30.0
    shape: bool = True
    # README rule's numeric form: target one prefill replica per
    # ``decode_per_prefill`` decode-capable replicas, bumped up when
    # handoff prefill failures climb, down when TPOT attainment drops
    decode_per_prefill: float = 3.0
    tpot_attain_low: float = 0.9


def desired_prefill(n_total: int, decode_per_prefill: float = 3.0,
                    bias: int = 0) -> int:
    """The README ratio rule, executable: prefill replicas for an
    ``n_total``-replica disaggregated fleet ("start 1 prefill per
    2-4 decode"), clamped so at least one replica of EACH class
    survives any shape move. ``bias`` is the signal correction:
    +1 when handoff prefill failures climb, -1 when TPOT attainment
    drops (grow the decode side)."""
    if n_total < 2:
        return 0
    want = round(n_total / (1.0 + decode_per_prefill)) + bias
    return max(1, min(n_total - 1, want))


class Autoscaler:
    """The closed-loop actuator. Owns the `FleetJournal`, consumes
    the `FleetMetrics` verdict→action latch, and performs journaled
    spawn/drain/rerole actions against the supervisor. All actions —
    loop-driven, forced (router ``autoscale`` op), or resumed from
    recovery — serialize on one lock: single action in flight,
    ever."""

    def __init__(self, supervisor, config: Optional[AutoscaleConfig]
                 = None, journal_path: Optional[str] = None,
                 flight=None):
        self.sup = supervisor
        self.cfg = config or AutoscaleConfig()
        if self.cfg.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.cfg.max_replicas < self.cfg.min_replicas:
            raise ValueError("max_replicas < min_replicas")
        path = journal_path or os.path.join(self.sup.log_dir,
                                            "fleet-journal.json")
        self.journal = FleetJournal(path)
        # flight recorder (r21 observability): every action commit/
        # rollback writes an ``autoscale`` bundle — the postmortem
        # shows what the actuator did before a crash
        self.flight = flight
        self.actions_total: Dict[Tuple[str, str], int] = {}
        self.last_action: Optional[Dict] = None
        self.recovery: Optional[Dict] = None
        self._last_up_t: Optional[float] = None
        self._last_down_t: Optional[float] = None
        self._handoff_fail_seen = 0
        self._action_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._pending_resumes: List[Dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # the supervisor stamps PT_SUPERVISOR_JOURNAL (+ replica idx)
        # into every replica env so recovery/straggler scans can
        # attribute orphans to this fleet
        self.sup.journal_path = path
        self.sup.autoscaler = self

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop,
                                        daemon=True,
                                        name="pt-autoscaler")
        self._thread.start()

    def stop(self, grace_s: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=grace_s)

    # -- recovery ----------------------------------------------------------

    def recover(self) -> Dict:
        """Adopt the previous supervisor generation's fleet. Must run
        BEFORE ``Supervisor.start()``: it REPLACES ``sup.replicas``
        with adopted (live, not re-spawned) + to-respawn records, so
        start() only spawns what recovery says is dead. Resumed
        half-finished actions queue for the loop's first tick (after
        the fleet is ready)."""
        from .supervisor import Replica

        body, err = load_journal(self.journal.path)
        scan = scan_marked_replicas(self.journal.path)
        report: Dict = {"journal": self.journal.path,
                        "loaded": body is not None,
                        "error": err, "adopted": [], "respawned": [],
                        "reaped": [], "resolved": [], "resumed": []}
        if body is None and not scan:
            self.recovery = report
            self.journal.record_fleet([])
            return report
        plan = plan_recovery(body, scan, self.cfg.min_replicas,
                             self.cfg.max_replicas)
        if body is not None:
            self.journal.adopt_body(body)
            # r24: restore the committed weight config BEFORE any
            # respawn — a dead replica must come back on the weights
            # the previous supervisor generation had rolled to
            cfg = body.get("config") or {}
            if cfg:
                self.sup.checkpoint = cfg.get("checkpoint")
                self.sup.weight_generation = int(
                    cfg.get("generation") or 0)
        replicas: List[Replica] = []
        for ent in plan["adopt"]:
            rep = Replica(int(ent["idx"]), self.sup.host)
            rep.port = ent.get("port")
            rep.role = (ent.get("role") if ent.get("role") in _ROLES
                        else "mixed")
            rep.proc = _AdoptedProc(int(ent["pid"]), ent.get("port"))
            rep.spawn_t = time.monotonic()
            rep.log_path = os.path.join(self.sup.log_dir,
                                        f"replica{rep.idx}.log")
            rep.draining = bool(ent.get("draining"))
            replicas.append(rep)
            report["adopted"].append(
                {"idx": rep.idx, "pid": ent["pid"],
                 "port": rep.port, "role": rep.role,
                 "draining": rep.draining})
        for ent in plan["respawn"]:
            rep = Replica(int(ent["idx"]), self.sup.host)
            rep.role = (ent.get("role") if ent.get("role") in _ROLES
                        else "mixed")
            replicas.append(rep)  # proc None: start() spawns it
            report["respawned"].append({"idx": rep.idx,
                                        "role": rep.role})
        for ent in plan["reap"]:
            proc = _AdoptedProc(int(ent["pid"]), ent.get("port"))
            proc.kill()
            try:
                proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                pass
            report["reaped"].append({"idx": ent.get("idx"),
                                     "pid": ent["pid"]})
        for seq, verdict, why in plan["resolve"]:
            if verdict == "commit":
                self.journal.commit(seq, resumed=why)
            else:
                self.journal.rollback(seq, reason=why)
            report["resolved"].append({"seq": seq, "phase": verdict,
                                       "reason": why})
        if replicas:
            self.sup.replicas = replicas
            self.sup._next_idx = max(r.idx for r in replicas) + 1
        self._pending_resumes = list(plan["resume"])
        report["resumed"] = list(plan["resume"])
        self.journal.record_fleet(self._fleet_entries())
        self.recovery = report
        return report

    # -- control loop ------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._tick()
            except Exception:
                # the actuator must never take the supervisor down;
                # a failed tick is retried next interval
                pass
            self._stop.wait(timeout=self.cfg.interval_s)

    def _tick(self) -> None:
        while self._pending_resumes and not self._stop.is_set():
            self._execute_resume(self._pending_resumes.pop(0))
        self._refresh_fleet_record()
        fleet = getattr(self.sup, "fleet", None)
        pressure = (fleet.consume_pressure()
                    if fleet is not None and
                    hasattr(fleet, "consume_pressure") else None)
        acted = False
        if pressure is not None:
            v = pressure.get("verdict")
            if v == "scale_up":
                acted = bool(self.scale_up(reason="pressure")
                             .get("ok"))
            elif v == "scale_down":
                acted = bool(self.scale_down(reason="pressure")
                             .get("ok"))
        if not acted and self.cfg.shape:
            plan = self.plan_shape()
            if plan is not None:
                self.rerole(plan["replica"], plan["role"],
                            reason=plan["reason"])

    def _execute_resume(self, resume: Dict) -> None:
        seq, idx = resume["seq"], resume["replica"]
        try:
            rep = self.sup._by_idx(idx)
        except KeyError:
            self.journal.rollback(seq, reason="resume_victim_lost")
            return
        if resume["action"] == "drain":
            self._finish_drain(rep, seq, reason="resume")
        elif resume["action"] == "rerole":
            self._finish_rerole(rep, resume.get("role", "mixed"),
                                seq, reason="resume")
        elif resume["action"] == "roll":
            self._finish_roll(resume, forward=True)
        elif resume["action"] == "roll_back":
            self._finish_roll(resume, forward=False)

    def _finish_roll(self, resume: Dict, forward: bool) -> None:
        """Converge an interrupted r24 weight roll. Forward: the swap
        was confirmed (or a sibling committed), so finish rolling the
        WHOLE fleet onto the target generation — roll_fleet skips
        already-converged replicas, making the resume idempotent.
        Backward: the swap was never confirmed, so converge every
        replica (including one whose swap landed just before the
        kill) back to the journal's committed config. The journal
        entry resolves only AFTER convergence — a crash mid-resume
        leaves it open for the next recovery."""
        seq = resume["seq"]
        ckpt = resume.get("checkpoint")
        gen = int(resume.get("generation") or 0)
        if forward:
            out = self.sup.roll_fleet(ckpt, generation=gen,
                                      canary_window_s=0.0,
                                      reason="resume")
            if out.get("ok"):
                self.journal.commit(seq, resumed="roll_resumed")
                self.journal.record_config(ckpt, gen)
                return
            # forward convergence failed (checkpoint gone / every
            # swap refused): fall back to the committed config so
            # the fleet is at least UNIFORM
            cfg = self.journal.config()
            self.sup._rollback_generation(
                cfg.get("checkpoint"),
                int(cfg.get("generation") or 0), self.journal,
                reason="roll_resume_failed")
            self.sup.checkpoint = cfg.get("checkpoint")
            self.sup.weight_generation = int(
                cfg.get("generation") or 0)
            self.journal.rollback(seq, reason="roll_resume_failed")
            return
        self.sup._rollback_generation(ckpt, gen, self.journal,
                                      reason="roll_incomplete")
        self.sup.checkpoint = ckpt
        self.sup.weight_generation = gen
        self.journal.rollback(seq, reason="roll_incomplete")

    def _refresh_fleet_record(self) -> None:
        """Keep the journal's fleet snapshot current with monitor
        respawns (pid/port churn without a scale action)."""
        cur = self._fleet_entries()
        if cur != self.journal.fleet():
            self.journal.record_fleet(cur)

    def _fleet_entries(self) -> List[Dict]:
        out = []
        for r in self.sup.replicas:
            out.append({"idx": r.idx,
                        "pid": (r.proc.pid if r.proc is not None
                                else None),
                        "port": r.port, "role": r.role})
        return out

    # -- shared action plumbing --------------------------------------------

    def _chaos_hold(self) -> None:
        """Deterministic SIGKILL window for the chaos harness: sleep
        inside the journaled-but-uncommitted span of every action.
        Zero-cost when PT_AUTOSCALE_HOLD_S is unset."""
        try:
            hold = float(os.environ.get("PT_AUTOSCALE_HOLD_S") or 0)
        except ValueError:
            hold = 0.0
        if hold > 0:
            time.sleep(hold)

    def _record(self, action: str, reason: str, ok: bool,
                **fields) -> Dict:
        with self._state_lock:
            key = (action, reason)
            self.actions_total[key] = self.actions_total.get(key,
                                                             0) + 1
            out = {"action": action, "reason": reason, "ok": ok,
                   "t_unix": time.time()}
            out.update(fields)
            self.last_action = out
        # bundle only actions that actually STARTED (journaled):
        # refusals are counters, not postmortems — an at_max refusal
        # re-fires every tick under sustained pressure and would
        # churn the flight ring's budget for nothing
        if self.flight is not None and action in ("spawn", "drain",
                                                  "rerole", "roll") \
                and not reason.startswith("refused_"):
            self.flight.record("autoscale", lambda: {
                "action": dict(out),
                "fleet": self._fleet_entries(),
                "journal_tail": self.journal.tail(16),
                "autoscaler": self.status()})
        return dict(out)

    def _refuse(self, action: str, why: str) -> Dict:
        return self._record(action, f"refused_{why}", ok=False)

    def _cooldown_left(self, direction: str, now: float) -> float:
        if direction == "up":
            last, cd = self._last_up_t, self.cfg.cooldown_up_s
        else:
            last, cd = self._last_down_t, self.cfg.cooldown_down_s
        if last is None:
            return 0.0
        return max(0.0, last + cd - now)

    def _wait_replica_ready(self, rep, timeout_s: float) -> bool:
        from .supervisor import _rpc
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline and not self._stop.is_set():
            if not rep.alive():
                return False
            try:
                h = _rpc(self.sup.host, rep.port, {"op": "health"},
                         timeout_s=self.sup.probe_timeout_s)
                if "status" in h:
                    return True
            except Exception:
                pass
            time.sleep(0.25)
        return False

    # -- actions -----------------------------------------------------------

    def scale_up(self, reason: str = "pressure",
                 role: str = "mixed", force: bool = False) -> Dict:
        """Journal begin → spawn → wait ready → commit → attach. A
        spawn that never goes ready is killed, rolled back, and still
        charged against the up-cooldown (a crash-looping image must
        not be retried at full rate)."""
        if role not in _ROLES:
            return self._refuse("spawn", f"bad_role_{role}")
        with self._action_lock:
            now = time.monotonic()
            if len(self.sup.replicas) >= self.cfg.max_replicas:
                return self._refuse("spawn", "at_max")
            if not force and self._cooldown_left("up", now) > 0:
                return self._refuse("spawn", "cooldown")
            rep = self.sup.add_replica(role=role, spawn=False)
            seq = self.journal.begin("spawn", replica=rep.idx,
                                     role=role, reason=reason)
            self.sup._spawn(rep)
            self.journal.update(seq, phase="launched",
                                pid=rep.proc.pid, port=rep.port)
            self._chaos_hold()
            ok = self._wait_replica_ready(
                rep, self.cfg.spawn_ready_timeout_s)
            self._last_up_t = now  # charged even on failure
            if not ok:
                try:
                    rep.proc.kill()
                    rep.proc.wait(timeout=10.0)
                except Exception:
                    pass
                rep.close_log()
                self.journal.rollback(seq, reason="never_ready")
                return self._record("spawn", "never_ready", ok=False,
                                    replica=rep.idx)
            # satellite fix (r21): the autoscaler probes its pending
            # spawn itself — without this reset a replica that
            # flapped before re-roling/adoption would carry max
            # backoff into its next legitimate respawn
            rep.reset_backoff()
            rep.ready = True
            self.journal.commit(seq)
            self.sup.attach_replica(rep)
            self.journal.record_fleet(self._fleet_entries())
            return self._record("spawn", reason, ok=True,
                                replica=rep.idx, port=rep.port,
                                seq=seq)

    def _pick_victim(self):
        """Least-loaded ready replica whose removal the scale-down
        guard allows (ties: highest idx — the newest one goes
        first)."""
        cands = []
        for r in self.sup.replicas:
            if getattr(r, "draining", False):
                continue
            if self.sup.scale_down_guard(
                    r.idx, min_replicas=self.cfg.min_replicas):
                continue
            cands.append(r)
        if not cands:
            return None
        return min(cands, key=lambda r: (getattr(r, "load", 0),
                                         -r.idx))

    def scale_down(self, reason: str = "pressure",
                   force: bool = False) -> Dict:
        """Guard → journal begin → drain (handoff, degrading to
        plain drain on failure) → kill → commit → detach. The
        replica set the router reads shrinks only after the commit
        (the draining flag already keeps new traffic off the
        victim)."""
        with self._action_lock:
            now = time.monotonic()
            if not force and self._cooldown_left("down", now) > 0:
                return self._refuse("drain", "cooldown")
            victim = self._pick_victim()
            if victim is None:
                return self._refuse("drain", "no_eligible_victim")
            seq = self.journal.begin(
                "drain", replica=victim.idx,
                pid=(victim.proc.pid if victim.proc else None),
                port=victim.port, role=victim.role, reason=reason)
            victim.draining = True
            self._chaos_hold()
            out = self._finish_drain(victim, seq, reason=reason)
            self._last_down_t = now
            return out

    def _finish_drain(self, victim, seq: int, reason: str) -> Dict:
        """The drain+kill+commit half — shared by fresh scale-downs
        and recovery resumes (drain is idempotent on the server:
        stop admitting, finish in-flight, return pages)."""
        victim.draining = True
        drain = self.sup.drain_replica(
            victim.idx, handoff=True,
            timeout_s=self.cfg.drain_timeout_s)
        if victim.proc is not None:
            try:
                victim.proc.terminate()
                victim.proc.wait(timeout=10.0)
            except Exception:
                try:
                    victim.proc.kill()
                    victim.proc.wait(timeout=10.0)
                except Exception:
                    pass
        victim.close_log()
        self.journal.commit(seq, drained=bool(drain.get("drained")),
                            handoff_failures=len(
                                (drain.get("handoff") or {})
                                .get("failures", ())))
        self.sup.remove_replica(victim)
        self.journal.record_fleet(self._fleet_entries())
        return self._record("drain", reason, ok=True,
                            replica=victim.idx, seq=seq,
                            drained=bool(drain.get("drained")))

    def rerole(self, idx: int, to_role: str,
               reason: str = "shape", force: bool = False) -> Dict:
        """Fleet-shape move: drain + restart ONE replica with a new
        ``--role`` instead of cold-spawning (its process slot, log
        and idx survive; its KV chains are handed to survivors
        first). Failure to come back ready degrades typed: the
        journal rolls back, the replica reverts to its old role and
        the monitor's respawn/backoff path owns recovery."""
        if to_role not in _ROLES:
            return self._refuse("rerole", f"bad_role_{to_role}")
        with self._action_lock:
            now = time.monotonic()
            if not force and self._cooldown_left("down", now) > 0:
                return self._refuse("rerole", "cooldown")
            try:
                rep = self.sup._by_idx(idx)
            except KeyError:
                return self._refuse("rerole", "no_such_replica")
            if rep.role == to_role:
                return self._refuse("rerole", "already_that_role")
            if self.sup.scale_down_guard(
                    idx, min_replicas=self.cfg.min_replicas):
                return self._refuse("rerole", "guard")
            seq = self.journal.begin(
                "rerole", replica=rep.idx,
                pid=(rep.proc.pid if rep.proc else None),
                port=rep.port, role_from=rep.role, role_to=to_role,
                reason=reason)
            rep.draining = True
            self._chaos_hold()
            out = self._finish_rerole(rep, to_role, seq,
                                      reason=reason)
            self._last_down_t = now
            return out

    def _finish_rerole(self, rep, to_role: str, seq: int,
                       reason: str) -> Dict:
        rep.draining = True
        old_role = rep.role
        self.sup.drain_replica(rep.idx, handoff=True,
                               timeout_s=self.cfg.drain_timeout_s)
        if rep.proc is not None:
            try:
                rep.proc.terminate()
                rep.proc.wait(timeout=10.0)
            except Exception:
                try:
                    rep.proc.kill()
                    rep.proc.wait(timeout=10.0)
                except Exception:
                    pass
        rep.role = to_role
        self.sup._spawn(rep)
        self.journal.update(seq, phase="launched", pid=rep.proc.pid,
                            port=rep.port)
        ok = self._wait_replica_ready(rep,
                                      self.cfg.spawn_ready_timeout_s)
        if not ok:
            try:
                rep.proc.kill()
            except Exception:
                pass
            rep.role = old_role
            rep.draining = False
            self.sup._mark_dead(rep)  # monitor respawns, old role
            self.journal.rollback(seq, reason="rerole_never_ready")
            return self._record("rerole", "rerole_never_ready",
                                ok=False, replica=rep.idx)
        rep.reset_backoff()
        rep.ready = True
        rep.draining = False
        self.journal.commit(seq)
        self.journal.record_fleet(self._fleet_entries())
        return self._record("rerole", reason, ok=True,
                            replica=rep.idx, role=to_role, seq=seq)

    # -- fleet shape (the README ratio rule, executable) -------------------

    def plan_shape(self) -> Optional[Dict]:
        """On a disaggregated fleet, compare the prefill-replica
        count against ``desired_prefill`` with the signal bias:
        handoff prefill failures climbing (scraped off the router)
        push the prefill side up; fleet TPOT attainment below
        ``tpot_attain_low`` pushes the decode side up. Returns a
        rerole proposal or None. Mixed replicas are the preferred
        conversion stock; with none left, the over-represented class
        donates."""
        reps = [r for r in self.sup.replicas
                if not getattr(r, "draining", False)]
        if len(reps) < 2 or all(r.role == "mixed" for r in reps):
            return None
        bias = 0
        router = getattr(self.sup, "router", None)
        if router is not None:
            fails = getattr(router,
                            "handoff_prefill_failures_total", 0)
            if fails > self._handoff_fail_seen:
                self._handoff_fail_seen = fails
                bias += 1
        tpot = self._tpot_attainment()
        if tpot is not None and tpot < self.cfg.tpot_attain_low:
            bias -= 1
        want = desired_prefill(len(reps),
                               self.cfg.decode_per_prefill, bias)
        n_prefill = sum(1 for r in reps if r.role == "prefill")
        if n_prefill < want:
            donor = next((r for r in reps if r.role == "mixed"),
                         None) or next(
                (r for r in reps if r.role == "decode"), None)
            if donor is not None and not self.sup.scale_down_guard(
                    donor.idx, min_replicas=self.cfg.min_replicas):
                return {"replica": donor.idx, "role": "prefill",
                        "reason": "shape_prefill_up"}
        elif n_prefill > want:
            donor = next((r for r in reps if r.role == "prefill"),
                         None)
            if donor is not None and not self.sup.scale_down_guard(
                    donor.idx, min_replicas=self.cfg.min_replicas):
                return {"replica": donor.idx, "role": "decode",
                        "reason": "shape_decode_up"}
        return None

    def _tpot_attainment(self) -> Optional[float]:
        fleet = getattr(self.sup, "fleet", None)
        if fleet is None:
            return None
        try:
            snap = fleet.fleet_snapshot()
            classes = (snap.get("slo") or {}).get("classes") or {}
            met = total = 0
            for c in classes.values():
                met += int(c.get("tpot_met") or 0)
                total += int(c.get("total") or 0)
            return (met / total) if total else None
        except Exception:
            return None

    # -- surfaces ----------------------------------------------------------

    def status(self) -> Dict:
        now = time.monotonic()
        with self._state_lock:
            by_role: Dict[str, int] = {}
            for r in self.sup.replicas:
                by_role[r.role] = by_role.get(r.role, 0) + 1
            return {
                "enabled": True,
                "min_replicas": self.cfg.min_replicas,
                "max_replicas": self.cfg.max_replicas,
                "replicas": len(self.sup.replicas),
                "replicas_by_role": by_role,
                "cooldown_up_s": self.cfg.cooldown_up_s,
                "cooldown_down_s": self.cfg.cooldown_down_s,
                "cooldown_up_remaining_s": round(
                    self._cooldown_left("up", now), 3),
                "cooldown_down_remaining_s": round(
                    self._cooldown_left("down", now), 3),
                "action_in_flight": self._action_lock.locked(),
                "last_action": (dict(self.last_action)
                                if self.last_action else None),
                "actions_total": {f"{a}|{r}": n for (a, r), n
                                  in sorted(
                                      self.actions_total.items())},
                "pending_resumes": len(self._pending_resumes),
                "journal": {"path": self.journal.path,
                            "seq": self.journal.seq,
                            "writes_total":
                                self.journal.writes_total,
                            "write_failures_total":
                                self.journal.write_failures_total},
                "recovery": self.recovery,
            }

    def prometheus_lines(self) -> List[str]:
        """The r21 observability families, appended to the router's
        ``fleet_metrics`` exposition."""
        with self._state_lock:
            totals = dict(self.actions_total)
        lines = ["# TYPE serving_autoscale_actions_total counter"]
        for (action, reason), n in sorted(totals.items()):
            lines.append(
                f'serving_autoscale_actions_total{{'
                f'action="{action}",reason="{reason}"}} {n}')
        lines.append("# TYPE serving_fleet_replicas gauge")
        by_role: Dict[str, int] = {}
        for r in self.sup.replicas:
            by_role[r.role] = by_role.get(r.role, 0) + 1
        for role in _ROLES:
            lines.append(f'serving_fleet_replicas{{role="{role}"}} '
                         f"{by_role.get(role, 0)}")
        return lines
