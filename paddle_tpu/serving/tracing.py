"""End-to-end request tracing (r16): span trees from router to engine.

One trace id follows a request through every hop the serving stack has
grown — FailoverRouter pick/forward/failover, replica receive,
scheduler queue, admission (prefix-cache match, spill-tier restore),
every prefill chunk, every decode/verify step, resurrection replay —
as a tree of timestamped spans. The reference framework treats tracing
as a first-class layer (platform/profiler.h RecordEvent host markers +
CUPTI device tracing); this is the serving-stack half of that idea:
the per-request, per-hop latency attribution that aggregate histograms
(serving/metrics.py) cannot give, and the input the ``serving_goodput``
bench computes SLO attainment from.

Design constraints (the hot-path contract):

- OFF BY DEFAULT costs ~zero: tracing is decided once per request by a
  deterministic sampler (``sample_rate``; an accumulator, not an RNG,
  so a 0.1 rate traces exactly every 10th request), and every hook in
  the engine is a single ``req.trace is None`` attribute check. No
  per-token allocation happens for unsampled requests.
- BOUNDED MEMORY: finished traces live in a fixed-size ring
  (``max_traces``); a runaway generation stops allocating spans at
  ``max_spans_per_trace`` and counts the overflow in
  ``dropped_spans`` instead of growing without bound.
- ONE TREE PER REQUEST across stitch points: resurrection replay and
  keyed router failover continue the SAME trace (the replayed/failed-
  over request's spans append to the original tree with explicit
  replay/failover markers), and every terminal path closes its open
  spans — ``leaked_open`` is pinned 0 by tests.

Span ids are strings namespaced per trace PARTICIPANT (process ×
trace instance), so router spans and replica spans for the same trace
id merge without collisions; a cross-process parent (the router's
forward span) is carried as ``remote_parent`` in the child root's args
— locally the tree stays orphan-free (tools/trace_lint.py), merged it
links into one tree.

Export: ``to_dict`` span trees (the ``trace`` server op / bench
input, validated by tools/trace_lint.py) and Chrome trace-event JSON
(``to_chrome`` / ``chrome_events``) mergeable with ``jax.profiler``
device traces via tools/merge_traces.py. When core/profiler.py is
enabled, finished spans are also injected as RecordEvent-compatible
host events, so ``export_chrome_trace`` shows serving spans next to
the jitted-step markers (which trace under ``jax.named_scope`` — see
the engine's step builders — and therefore appear inside XLA traces).

Debug mode: PT_SERVING_DEBUG=1 (see server.py) is now this tracer at
``sample_rate=1.0`` with the ``stderr_span_sink`` — one event
vocabulary for lifecycle debugging and trace export, replacing the
ad-hoc r9 print sites.
"""

from __future__ import annotations

import itertools
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["Span", "RequestTrace", "SpanTracer", "stderr_span_sink",
           "chrome_events", "request_latencies"]


def now_us() -> float:
    """The tracer clock: time.monotonic in microseconds (the same
    clock the engine's RequestStats use, so spans and stats agree)."""
    return time.monotonic() * 1e6


# per-process participant counter: each RequestTrace instance gets a
# unique segment so span ids from different processes (router vs
# replica) or trace instances never collide when merged
_SEG = itertools.count()


class Span:
    """One timed operation in a trace. ``t1_us`` is None while open."""

    __slots__ = ("sid", "parent", "name", "t0_us", "t1_us", "args")

    def __init__(self, sid: str, parent: Optional[str], name: str,
                 t0_us: float, args: Dict[str, Any]):
        self.sid = sid
        self.parent = parent
        self.name = name
        self.t0_us = t0_us
        self.t1_us: Optional[float] = None
        self.args = args

    def to_dict(self) -> Dict[str, Any]:
        return {"sid": self.sid, "parent": self.parent,
                "name": self.name, "t0_us": self.t0_us,
                "t1_us": self.t1_us, "args": dict(self.args)}


class RequestTrace:
    """The span tree of one request (one participant's share of it).

    Span mutation is engine-thread-dominant but submit/finish can run
    on connection threads; a small lock guards the id counter and the
    span list. All methods are no-op-cheap — the expensive decision
    (to trace at all) was made once at sampling time."""

    __slots__ = ("trace_id", "pid", "spans", "anchor", "state",
                 "dropped_spans", "leaked_open", "_seg", "_n",
                 "_lock", "_tracer", "_max_spans", "_finished")

    def __init__(self, trace_id: str, tracer: "SpanTracer",
                 max_spans: int):
        self.trace_id = trace_id
        self.pid = os.getpid()
        self.spans: List[Span] = []
        self.anchor: Optional[Span] = None  # the root/stage parent
        self.state: Optional[str] = None
        self.dropped_spans = 0
        self.leaked_open = 0
        self._seg = f"{self.pid:x}.{next(_SEG):x}"
        self._n = 0
        self._lock = threading.Lock()
        self._tracer = tracer
        self._max_spans = max_spans
        self._finished = False

    # -- span construction -------------------------------------------------

    def _new(self, name: str, parent, t0_us: float,
             args: Dict[str, Any]) -> Optional[Span]:
        pid_ = parent.sid if isinstance(parent, Span) else parent
        with self._lock:
            if self._finished or len(self.spans) >= self._max_spans:
                self.dropped_spans += 1
                return None
            self._n += 1
            sp = Span(f"{self._seg}:{self._n}", pid_, name, t0_us, args)
            self.spans.append(sp)
        return sp

    def begin(self, name: str, parent=None, **args) -> Optional[Span]:
        sp = self._new(name, parent, now_us(), args)
        if sp is not None:
            self._tracer._on_span("begin", self, sp)
        return sp

    def end(self, span: Optional[Span], **args) -> None:
        if span is None or span.t1_us is not None:
            return
        span.t1_us = now_us()
        if args:
            span.args.update(args)
        self._tracer._on_span("end", self, span)

    def add(self, name: str, t0_us: float, t1_us: float, parent=None,
            **args) -> Optional[Span]:
        """Append an already-timed (closed) span — the per-step path:
        the engine measures one decode/verify interval and attributes
        it to every sampled in-flight request without re-reading the
        clock per slot."""
        sp = self._new(name, parent, t0_us, args)
        if sp is not None:
            sp.t1_us = t1_us
            self._tracer._on_span("end", self, sp)
        return sp

    def event(self, name: str, parent=None, **args) -> Optional[Span]:
        """Zero-duration marker (first_token, complete, replay...)."""
        t = now_us()
        sp = self._new(name, parent, t, args)
        if sp is not None:
            sp.t1_us = t
            self._tracer._on_span("event", self, sp)
        return sp

    # -- wire context ------------------------------------------------------

    def ctx(self, parent=None) -> Dict[str, Any]:
        """The wire form another process continues this trace from:
        the receiving side adopts the id and records ``parent`` as its
        root's ``remote_parent`` (cross-process links stay out of the
        local parent field so a single participant's dump is still
        orphan-free for trace_lint)."""
        p = parent.sid if isinstance(parent, Span) else parent
        return {"id": self.trace_id, "parent": p, "sampled": True}

    # -- introspection -----------------------------------------------------

    def open_spans(self) -> int:
        with self._lock:
            return sum(1 for s in self.spans if s.t1_us is None)

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            return {"trace_id": self.trace_id, "pid": self.pid,
                    "state": self.state,
                    "dropped_spans": self.dropped_spans,
                    "leaked_open": self.leaked_open,
                    "spans": [s.to_dict() for s in self.spans]}


class SpanTracer:
    """Sampling, bounded-memory span tracer (the serving tentpole).

    ``sample_rate`` in [0, 1]: deterministic accumulator sampling.
    ``on_span(kind, trace_id, span_dict)`` is the optional live sink
    (``stderr_span_sink`` — the PT_SERVING_DEBUG lifecycle stream);
    ``profiler_bridge`` additionally injects finished spans into
    core/profiler.py's host-event buffer whenever that profiler is
    enabled, so one ``export_chrome_trace`` carries both."""

    def __init__(self, sample_rate: float = 0.0, max_traces: int = 64,
                 max_spans_per_trace: int = 4096,
                 on_span: Optional[Callable] = None,
                 profiler_bridge: bool = True):
        self.sample_rate = float(sample_rate)
        if not 0.0 <= self.sample_rate <= 1.0:
            raise ValueError(
                f"sample_rate must be in [0, 1], got {self.sample_rate}")
        self.max_spans_per_trace = int(max_spans_per_trace)
        self.on_span = on_span
        self.profiler_bridge = bool(profiler_bridge)
        self._ring: "deque[Dict]" = deque(maxlen=int(max_traces))
        self._events: "deque[Dict]" = deque(maxlen=256)
        self._acc = 0.0
        self._nid = itertools.count()
        self._lock = threading.Lock()
        # lifetime counters (exported as serving_traces_* series)
        self.sampled_total = 0
        self.finished_total = 0
        self.spans_dropped_total = 0

    # -- sampling / lifecycle ----------------------------------------------

    def sample(self) -> bool:
        """Deterministic: rate 1.0 samples everything, 0.25 every 4th
        request — no RNG on the submit path, reproducible in tests."""
        if self.sample_rate <= 0.0:
            return False
        with self._lock:
            self._acc += self.sample_rate
            if self._acc >= 1.0 - 1e-9:
                self._acc -= 1.0
                return True
        return False

    def start(self, name: str, ctx: Optional[Dict] = None,
              sampled: Optional[bool] = None, **args
              ) -> Optional[RequestTrace]:
        """Open a new trace with root span ``name``; returns None when
        the request is not sampled. ``ctx`` (a ``RequestTrace.ctx()``
        dict from another hop) forces sampling and adopts its id."""
        if ctx is not None and isinstance(ctx, dict) and ctx.get("id"):
            tid = str(ctx["id"])
            take = True
            if ctx.get("parent"):
                args.setdefault("remote_parent", str(ctx["parent"]))
        else:
            take = sampled if sampled is not None else self.sample()
            if not take:
                return None
            tid = (f"{os.getpid():x}-{next(self._nid):x}-"
                   f"{time.time_ns() & 0xffffffff:08x}")
        with self._lock:
            self.sampled_total += 1
        tr = RequestTrace(tid, self, self.max_spans_per_trace)
        tr.anchor = tr.begin(name, **args)
        return tr

    def finish(self, trace: Optional[RequestTrace],
               state: Optional[str] = None) -> None:
        """Close the root, force-close stragglers (counted in
        ``leaked_open`` — the zero the stitch-point tests pin), and
        move the trace into the finished ring."""
        if trace is None or trace._finished:
            return
        if state is not None:
            trace.state = state
        if trace.anchor is not None and trace.anchor.t1_us is None:
            trace.end(trace.anchor, state=trace.state)
        t = now_us()
        with trace._lock:
            for s in trace.spans:
                if s.t1_us is None:
                    s.t1_us = t
                    s.args["leaked_open"] = True
                    trace.leaked_open += 1
            trace._finished = True
        with self._lock:
            self.finished_total += 1
            self.spans_dropped_total += trace.dropped_spans
            self._ring.append(trace.to_dict())

    # -- sinks -------------------------------------------------------------

    def _on_span(self, kind: str, trace: RequestTrace, span: Span
                 ) -> None:
        if self.on_span is not None:
            try:
                self.on_span(kind, trace.trace_id, span.to_dict())
            except Exception:
                pass  # a sink must never break the serving path
        if kind != "begin" and self.profiler_bridge \
                and span.t1_us is not None:
            _bridge_profiler(trace.trace_id, span)

    def annotate(self, name: str, **args) -> None:
        """Tracer-level event not tied to one request (resurrection
        snapshots, router restarts) — bounded ring + live sink; the
        chaos-postmortem channel the old debug prints served."""
        ev = {"name": name, "t_us": now_us(), "args": args}
        with self._lock:
            self._events.append(ev)
        if self.on_span is not None:
            try:
                self.on_span("annotate", None,
                             {"name": name, "t0_us": ev["t_us"],
                              "t1_us": ev["t_us"], "args": args,
                              "sid": None, "parent": None})
            except Exception:
                pass

    # -- export ------------------------------------------------------------

    def finished(self, n: Optional[int] = None) -> List[Dict]:
        with self._lock:
            out = list(self._ring)
        return out if n is None else out[-int(n):]

    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._events)

    def drain(self) -> List[Dict]:
        with self._lock:
            out = list(self._ring)
            self._ring.clear()
        return out

    def to_chrome(self, traces: Optional[List[Dict]] = None) -> Dict:
        """Chrome trace-event JSON of finished traces — the format
        tools/merge_traces.py merges with ``jax.profiler`` output."""
        evs: List[Dict] = []
        for t in (self.finished() if traces is None else traces):
            evs.extend(chrome_events(t))
        return {"traceEvents": evs}


def chrome_events(trace: Dict) -> List[Dict]:
    """One finished-trace dict -> chrome 'X' events (one tid per
    trace, so each request renders as its own row)."""
    tid = abs(hash(trace.get("trace_id", ""))) % 1_000_000
    out = []
    for s in trace.get("spans", ()):
        t0 = s.get("t0_us", 0.0)
        t1 = s.get("t1_us")
        args = dict(s.get("args") or {})
        args["trace_id"] = trace.get("trace_id")
        if s.get("sid"):
            args["sid"] = s["sid"]
        if s.get("parent"):
            args["parent"] = s["parent"]
        out.append({"name": s.get("name", "?"), "ph": "X", "ts": t0,
                    "dur": max((t1 if t1 is not None else t0) - t0,
                               0.01),
                    "pid": trace.get("pid", 0), "tid": tid,
                    "args": args})
    return out


def request_latencies(trace: Dict) -> Optional[Dict[str, float]]:
    """TTFT / TPOT / e2e of one finished request trace — the numbers
    the serving_goodput bench computes SLO attainment from. Returns
    None when the trace lacks the lifecycle markers (e.g. a shed
    request that never produced a token)."""
    submit = first = complete = None
    tokens_out = pre_tokens = 0
    priority = None
    for s in trace.get("spans", ()):
        name = s.get("name")
        if name == "queue" and submit is None:
            submit = s.get("t0_us")
            p = (s.get("args") or {}).get("priority")
            if isinstance(p, int) and not isinstance(p, bool):
                priority = p
        elif name == "first_token" and first is None:
            first = s.get("t0_us")
        elif name == "complete":
            complete = s.get("t0_us")
            tokens_out = int((s.get("args") or {}).get("tokens_out", 0))
        elif name == "resurrect_replay":
            # a stitched tree's 'complete' counts only the FINAL
            # replay slice's tokens (the engine restarts generated[]
            # per replay); each resurrect marker carries its dying
            # slice's count — the client-experienced total is the sum
            pre_tokens += int((s.get("args") or {}).get(
                "pre_tokens", 0))
    if submit is None or complete is None:
        return None
    tokens_out += pre_tokens
    out = {"submit_us": submit, "complete_us": complete,
           "tokens_out": tokens_out,
           # the queue span's priority arg (None when untraced) — the
           # fleet SLO monitor tracks attainment per class, and the
           # trace-computed attainment must split the same way (r17)
           "priority": priority,
           "e2e_s": (complete - submit) / 1e6,
           "ttft_s": None, "tpot_s": None}
    if first is not None:
        out["first_token_us"] = first
        out["ttft_s"] = (first - submit) / 1e6
        if tokens_out > 1:
            out["tpot_s"] = ((complete - first) / 1e6
                             / (tokens_out - 1))
    return out


def stderr_span_sink(kind: str, trace_id: Optional[str],
                     span: Dict) -> None:
    """The PT_SERVING_DEBUG live sink: one line per span begin/end and
    tracer annotation on stderr — the unified replacement for the r9
    ad-hoc lifecycle prints (same information, one event vocabulary)."""
    args = span.get("args") or {}
    kv = " ".join(f"{k}={v}" for k, v in args.items())
    tid = (trace_id or "-")[-12:]
    dur = ""
    if kind == "end" and span.get("t1_us") is not None:
        dur = f" {(span['t1_us'] - span['t0_us']) / 1e3:.3f}ms"
    print(f"[pt-serving-trace {time.monotonic():.3f}] {kind} "
          f"{span.get('name')} trace={tid}{dur} {kv}".rstrip(),
          file=sys.stderr, flush=True)


def _bridge_profiler(trace_id: str, span: Span) -> None:
    """Inject a closed span into core/profiler.py's host-event buffer
    when that profiler is enabled — serving spans then ride the same
    ``export_chrome_trace`` as the RecordEvent markers."""
    try:
        from ..core import profiler
    except Exception:  # profiler imports jax; never break serving
        return
    if not getattr(profiler, "profiler_active", lambda: False)():
        return
    try:
        profiler.external_event(span.name, span.t0_us, span.t1_us,
                                annotation=trace_id)
    except Exception:
        pass
