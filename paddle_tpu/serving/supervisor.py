"""Replica supervision and failover routing for the serving layer.

One `ServingServer` process is one fault domain: engine resurrection
(server.py) survives anything below the socket, but a SIGKILL, an OOM
or a wedged interpreter takes the whole replica with it. This module
is the layer above: a `Supervisor` that spawns N server PROCESSES,
health-probes them over the wire, restarts crashed replicas with
exponential backoff, and a `FailoverRouter` that fronts them on one
port — a request whose replica dies mid-flight is resubmitted to a
live replica when it is idempotent (carries a ``key``), so the client
sees a pause instead of a torn connection.

Idempotency contract: greedy decoding is deterministic (the serving
suite pins bit-identical outputs across prefix caching, speculation
and engine resurrection), so resubmitting a keyed request re-derives
exactly the tokens the dead replica would have produced. The router
counts the token messages it already relayed and suppresses that many
from the resubmitted stream — the client's stream continues seamlessly.
Unkeyed requests get a typed retryable ``ReplicaFailed`` instead (the
router must not guess at idempotency).

Fleet telemetry plane (r17, serving/fleet_metrics.py): each healthy
probe cycle also scrapes the replica's STRUCTURED metrics export
(``{"op": "export"}``) into a supervisor-side collector that merges
histograms bucket-exactly, tracks fleet SLO attainment, classifies
probe failures (timeout/refused/malformed/...), flags outlier
replicas against the fleet median, and publishes it all through the
router's ``fleet_stats`` (JSON) and ``fleet_metrics`` (Prometheus,
``replica``-labeled series + ``fleet_*`` rollups) ops.

Fault sites (distributed/fault_inject.py): ``net.recv`` fires in the
router's backend reader — an armed schedule makes the router treat the
backend as dead and exercise the failover path; the same site inside a
replica's server tears the backend connection for real.

Run it::

    python -m paddle_tpu.serving.supervisor --replicas 2 \
        --model gpt_125m --port 8770

Reference analog: the fleet elastic controller (ELASTIC_EXIT_CODE
restart contract, PR 1) applied to the serving tier — supervision as
an external process loop, recovery as resubmission over a
deterministic engine.
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Replica", "Supervisor", "FailoverRouter",
           "classify_probe_failure", "handoff_chains",
           "rendezvous_owner"]


def _free_port(host: str = "127.0.0.1") -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _rpc(host: str, port: int, payload: Dict, timeout_s: float) -> Dict:
    """One request/one reply over a fresh connection (health probes,
    admin ops). Raises OSError family on a dead backend."""
    with socket.create_connection((host, port),
                                  timeout=timeout_s) as s:
        f = s.makefile("rw", encoding="utf-8")
        f.write(json.dumps(payload) + "\n")
        f.flush()
        line = f.readline()
        if not line:
            raise ConnectionError("backend closed without replying")
        return json.loads(line)


def classify_probe_failure(exc: Optional[BaseException]) -> str:
    """Probe-failure taxonomy (r17): map a probe exception (None = the
    reply arrived but was malformed) onto a stable kind. The monitor
    loop keeps per-replica counts per kind — a replica that TIMES OUT
    (wedged/overloaded) and one REFUSING connections (dead port) and
    one answering GARBAGE (torn/buggy) are different incidents."""
    if exc is None:
        return "malformed"
    if isinstance(exc, socket.timeout):
        return "timeout"
    if isinstance(exc, ConnectionRefusedError):
        return "refused"
    if isinstance(exc, ConnectionResetError):
        return "reset"
    if isinstance(exc, json.JSONDecodeError):
        return "torn_json"
    if isinstance(exc, ConnectionError):
        return "closed"
    if isinstance(exc, OSError):
        return "os_error"
    return "error"


class Replica:
    """One supervised server process."""

    def __init__(self, idx: int, host: str):
        self.idx = idx
        self.host = host
        self.port: Optional[int] = None
        self.proc: Optional[subprocess.Popen] = None
        self.ready = False
        self.restarts = 0           # respawns after a death
        self.consec_deaths = 0      # resets on a healthy probe
        self.probe_failures = 0
        # probe-failure taxonomy (r17): a bare "ok = False" collapsed
        # timeout/refused/malformed into one signal — these keep the
        # per-kind lifetime counts + the most recent classified error,
        # exported through fleet_stats (a replica that times out under
        # load and one that answers garbage need different operators)
        self.probe_failures_by_kind: Dict[str, int] = {}
        self.last_probe_error: Optional[str] = None
        self.next_spawn_t: Optional[float] = None  # backoff gate
        self.spawn_t: Optional[float] = None       # warmup clock
        self.log_path: Optional[str] = None
        self._log_file = None
        # cache-affinity advertisement (r15): refreshed from every
        # healthy probe — the chain-head prefix keys this replica's
        # cache can serve, its page size (the router needs it to hash
        # a prompt's first block), and its current load (the
        # least-loaded fallback's input)
        self.prefix_keys: frozenset = frozenset()
        self.page_size: Optional[int] = None
        self.load: int = 0
        # disaggregated serving (r20): the replica's class (refreshed
        # from health; the supervisor seeds it from its roles list so
        # routing is correct from the first probe) and whether its
        # prefix-key advertisement was recency-capped — a truncated
        # list means "not advertised" is NOT "not resident"
        self.role: str = "mixed"
        self.prefix_truncated: bool = False
        # memory observatory (r18): the replica's latest capacity-op
        # reply (occupancy by owner class + exhaustion forecast),
        # refreshed each healthy probe cycle — fleet_capacity merges
        # the fresh ones
        self.capacity: Optional[Dict] = None
        self.capacity_t: float = 0.0
        # autoscaling (r21): a draining victim is mid-scale-down or
        # mid-rerole — the monitor must not respawn its deliberate
        # kill and the router must not route to it
        self.draining = False
        # weight hot-swap (r24): the replica's serving weight
        # generation, refreshed from every healthy probe — roll_fleet
        # reads it to skip already-converged replicas, fleet_stats
        # rolls it up so a mixed-generation fleet is visible
        self.weight_generation: int = 0

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def reset_backoff(self) -> None:
        """A healthy probe clears the crash-loop state. One definition
        for every probe path (monitor loop, autoscaler ready-checks):
        before r21 only the monitor reset, so a replica that flapped
        during a scale storm carried max backoff into its next
        legitimate respawn."""
        self.consec_deaths = 0
        self.probe_failures = 0
        self.next_spawn_t = None

    def close_log(self) -> None:
        if self._log_file is not None:
            try:
                self._log_file.close()
            except OSError:
                pass
            self._log_file = None


class Supervisor:
    """Spawn, probe, and resurrect N serving replicas.

    ``server_args`` are appended to every replica's command line
    (e.g. ``["--page-size", "8", "--stall-timeout-s", "30"]``);
    ``replica_env`` overlays the inherited environment — chaos runs
    arm PT_FAULT_INJECT there, CPU test runs pin JAX_PLATFORMS=cpu.
    A dead replica respawns after ``backoff_base_s * 2**consec_deaths``
    (capped at ``backoff_max_s``) on a FRESH port; a ready replica that
    fails ``max_probe_failures`` consecutive health probes is killed
    and treated as dead (half-alive processes hold no traffic)."""

    def __init__(self, model: str = "gpt_125m", replicas: int = 2,
                 host: str = "127.0.0.1",
                 server_args: Sequence[str] = (),
                 replica_env: Optional[Dict[str, str]] = None,
                 probe_interval_s: float = 0.5,
                 probe_timeout_s: float = 5.0,
                 max_probe_failures: int = 3,
                 backoff_base_s: float = 0.5,
                 backoff_max_s: float = 10.0,
                 ready_timeout_s: float = 300.0,
                 log_dir: Optional[str] = None,
                 collect_metrics: bool = True,
                 fleet=None,
                 roles: Optional[Sequence[str]] = None,
                 checkpoint: Optional[str] = None,
                 weight_generation: int = 0):
        self.model = model
        self.host = host
        self.server_args = list(server_args)
        # weight hot-swap (r24): the fleet's COMMITTED weight source —
        # every (re)spawn, monitor respawn and re-role boots from this
        # checkpoint at this generation, so a replica that crashes
        # after a roll comes back on the ROLLED weights, not the boot
        # image. roll_fleet advances both once the canary commits.
        self.checkpoint = checkpoint
        self.weight_generation = int(weight_generation)
        self.replica_env = dict(replica_env or {})
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.max_probe_failures = int(max_probe_failures)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.ready_timeout_s = float(ready_timeout_s)
        # fleet telemetry plane (r17): a healthy probe cycle also
        # scrapes the replica's STRUCTURED metrics export into the
        # collector (ServingMetrics.export() over the wire — never
        # parsed exposition text); collect_metrics=False is the
        # scrape-overhead escape hatch the fleet_goodput bench A/Bs
        self.collect_metrics = bool(collect_metrics)
        if fleet is not None:
            self.fleet = fleet
        elif collect_metrics:
            from .fleet_metrics import FleetMetrics
            self.fleet = FleetMetrics(
                stale_after_s=max(10.0, 4 * float(probe_interval_s)))
        else:
            self.fleet = None
        if log_dir is None:
            self.log_dir = tempfile.mkdtemp(
                prefix="pt-serving-supervisor-")
        else:
            self.log_dir = log_dir
            os.makedirs(log_dir, exist_ok=True)
        self.replicas: List[Replica] = [Replica(i, host)
                                        for i in range(int(replicas))]
        # disaggregated roles (r20): one role per replica ("mixed" /
        # "prefill" / "decode"), threaded to each server as --role and
        # seeded on the Replica records so the router's role-aware
        # dispatch is correct from the first probe. A shorter list
        # pads with "mixed".
        self.roles: List[str] = []
        roles = list(roles or ())
        for i, rep in enumerate(self.replicas):
            role = roles[i] if i < len(roles) else "mixed"
            if role not in ("mixed", "prefill", "decode"):
                raise ValueError(
                    f"replica role must be mixed/prefill/decode; got "
                    f"{role!r} for replica {i}")
            rep.role = role
            self.roles.append(role)
        # autoscaling actuator (r21): `Autoscaler` attaches itself
        # here and sets journal_path so _spawn can stamp the env
        # markers recovery scans for; the router back-references
        # itself for the shape planner's handoff-failure signal
        self.autoscaler = None
        self.journal_path: Optional[str] = None
        self.router = None
        self._next_idx = int(replicas)
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    def start(self, wait_ready: bool = True) -> None:
        # spawn-if-unspawned: after autoscaler recovery the list holds
        # ADOPTED replicas (live process from the previous supervisor
        # generation, proc already set) next to to-respawn records
        # (proc None) — only the latter get a fresh process
        for rep in self.replicas:
            if rep.proc is None:
                self._spawn(rep)
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True,
                                         name="pt-supervisor-monitor")
        self._monitor.start()
        if wait_ready:
            self.wait_ready()

    def wait_ready(self, min_ready: Optional[int] = None) -> None:
        """Block until ``min_ready`` replicas (default: all) answer a
        health probe; raises with the laggards' log paths on timeout
        (the logs hold the subprocess traceback)."""
        if min_ready is None:
            want = len([r for r in self.replicas if not r.draining])
        else:
            want = min_ready
        deadline = time.monotonic() + self.ready_timeout_s
        while time.monotonic() < deadline:
            if sum(r.ready for r in self.replicas
                   if not r.draining) >= want:
                return
            if self._stop.is_set():
                raise RuntimeError("supervisor stopped while waiting")
            time.sleep(0.1)
        lag = [(r.idx, r.log_path) for r in self.replicas
               if not r.ready]
        raise RuntimeError(
            f"replicas not ready after {self.ready_timeout_s}s: {lag}")

    def stop(self, drain: bool = True, grace_s: float = 10.0) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=grace_s)
        reps = list(self.replicas)  # autoscaler churn: fixed snapshot
        for rep in reps:
            if rep.alive() and drain:
                try:
                    _rpc(self.host, rep.port, {"op": "drain"},
                         timeout_s=2.0)
                except Exception:
                    pass
        for rep in reps:
            if rep.alive():
                rep.proc.terminate()
        deadline = time.monotonic() + grace_s
        for rep in reps:
            if rep.proc is None:
                continue
            left = max(0.1, deadline - time.monotonic())
            try:
                rep.proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                rep.proc.kill()
                rep.proc.wait(timeout=5.0)
            rep.close_log()

    def __enter__(self) -> "Supervisor":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- chaos hooks -------------------------------------------------------

    def kill_replica(self, idx: int,
                     sig: int = signal.SIGKILL) -> None:
        """Chaos entry: deliver ``sig`` to one replica process (the
        monitor notices the death and respawns it with backoff)."""
        rep = self._by_idx(idx)
        if rep.alive():
            rep.proc.send_signal(sig)

    def _by_idx(self, idx: int) -> Replica:
        """Replica by its idx FIELD — under autoscaling the list is no
        longer position-indexed (scale-down leaves holes)."""
        for r in self.replicas:
            if r.idx == idx:
                return r
        raise KeyError(f"no replica with idx {idx}")

    # -- autoscaling membership (r21) --------------------------------------

    def add_replica(self, role: str = "mixed",
                    spawn: bool = True) -> Replica:
        """Allocate the next replica record. ``spawn=False`` leaves it
        DETACHED (not in ``self.replicas``): the autoscaler journals
        the intent, spawns, waits ready, and only then attaches — the
        router never routes to a pending spawn."""
        if role not in ("mixed", "prefill", "decode"):
            raise ValueError(f"bad role {role!r}")
        with self._lock:
            rep = Replica(self._next_idx, self.host)
            self._next_idx += 1
        rep.role = role
        if spawn:
            self._spawn(rep)
            self.attach_replica(rep)
        return rep

    def attach_replica(self, rep: Replica) -> None:
        """Publish a replica to the router/monitor (idempotent). The
        list is REBOUND, never mutated in place — readers iterate a
        consistent snapshot without taking the lock."""
        with self._lock:
            if all(r.idx != rep.idx for r in self.replicas):
                self.replicas = self.replicas + [rep]

    def remove_replica(self, rep: Replica) -> None:
        with self._lock:
            self.replicas = [r for r in self.replicas
                             if r.idx != rep.idx]
        rep.close_log()
        if self.fleet is not None:
            self.fleet.mark_stale(rep.idx)

    def scale_down_guard(self, idx: int,
                         min_replicas: int = 1) -> Optional[str]:
        """Why removing replica ``idx`` must be REFUSED, or None when
        the removal is safe (satellite fix, r21): an empty survivor
        set, a survivor set below the min-replica envelope, or — on a
        disaggregated fleet — losing the last replica advertising a
        role would strand traffic, so the refusal is typed here
        instead of crashing or stranding downstream."""
        try:
            rep = self._by_idx(idx)
        except KeyError:
            return "no_such_replica"
        survivors = [r for r in self.replicas
                     if r.idx != idx and not r.draining]
        if not survivors:
            return "last_replica"
        if len(survivors) < min_replicas:
            return f"below_min_replicas({min_replicas})"
        if rep.role in ("prefill", "decode") and \
                not any(r.role == rep.role for r in survivors):
            return f"last_{rep.role}_replica"
        return None

    def drain_replica(self, idx: int, handoff: bool = True,
                      timeout_s: float = 30.0,
                      min_replicas: int = 1) -> Dict:
        """Scale-down drain with prefix-affinity-aware handoff (r20,
        the missing ROADMAP 3(a) drain): refresh the victim's
        advertisement, hand its hot chains to the surviving
        decode-capable replicas through the fetch_pages path (each
        survivor pulls its rendezvous share DIRECTLY from the victim),
        then drain the victim — stop admitting, finish in-flight,
        return every page. The victim process is left alive for the
        caller to reap (or the monitor to respawn); handoff failures
        degrade to re-prefill-on-first-use, never block the drain.

        Refuses TYPED (r21 satellite fix) when the guard says removal
        would empty the fleet, drop below ``min_replicas``, or lose
        the last replica of a role — ``{"refused": <reason>}`` instead
        of a crash or a stranded fleet. A victim already mid-drain
        (``rep.draining``) skips the guard: the autoscaler's recovery
        path re-drains an adopted victim whose removal was already
        committed to."""
        rep = self._by_idx(idx)
        if not rep.draining:
            guard = self.scale_down_guard(idx,
                                          min_replicas=min_replicas)
            if guard is not None:
                return {"victim": idx, "refused": guard,
                        "handoff": None, "drained": False}
        report: Dict = {"victim": idx, "handoff": None,
                        "drained": False}
        if handoff and rep.alive():
            heads: List[str] = list(rep.prefix_keys)
            try:
                h = _rpc(self.host, rep.port, {"op": "health"},
                         timeout_s=timeout_s)
                heads = list(h.get("prefix_keys") or heads)
            except Exception:
                pass  # stale advertisement is still worth handing off
            survivors = [r for r in self.live()
                         if r.idx != idx and r.role != "prefill"]
            if heads and survivors:
                report["handoff"] = handoff_chains(
                    self.host, rep.port, heads, survivors,
                    timeout_s=timeout_s)
        try:
            _rpc(self.host, rep.port, {"op": "drain"},
                 timeout_s=timeout_s)
            report["drained"] = True
        except Exception as e:
            report["drain_error"] = f"{type(e).__name__}: {e}"
        return report

    # -- rolling weight upgrade (r24) --------------------------------------

    def _probe_generation(self, rep: Replica) -> Optional[int]:
        """The replica's CURRENT weight generation, probed live (the
        scraped ``rep.weight_generation`` can lag a probe cycle).
        None on a dead/unreachable replica."""
        try:
            h = _rpc(self.host, rep.port, {"op": "health"},
                     timeout_s=self.probe_timeout_s)
            g = h.get("weight_generation")
            if isinstance(g, int) and not isinstance(g, bool):
                return g
        except Exception:
            pass
        return None

    def _fleet_attainment(self) -> Optional[float]:
        """Merged fleet SLO attainment (r17 monitor) as one fraction —
        the canary window's regression baseline. None when the fleet
        plane is off or no SLO targets are armed."""
        if self.fleet is None:
            return None
        try:
            snap = self.fleet.fleet_snapshot()
            classes = (snap.get("slo") or {}).get("classes") or {}
            met = total = 0
            for c in classes.values():
                met += (int(c.get("ttft_met") or 0)
                        + int(c.get("tpot_met") or 0))
                total += 2 * int(c.get("total") or 0)
            return (met / total) if total else None
        except Exception:
            return None

    def _watch_canary(self, canary: Replica, window_s: float,
                      baseline: Optional[float], slo_regress: float,
                      canary_check=None) -> Optional[str]:
        """Observe the first swapped replica for ``window_s`` before
        the roll proceeds. Returns a typed regression reason (the
        auto-rollback trigger) or None:

        - the canary dying or failing 3 consecutive probes — the
          EngineFailed class the ISSUE names;
        - the r17 outlier detector flagging it (erroring / slow vs
          the fleet median — the error-rate signal);
        - fleet SLO attainment dropping more than ``slo_regress``
          below the pre-roll baseline;
        - a truthy string from an injected ``canary_check()`` (the
          operator/test hook), checked every probe interval."""
        if window_s <= 0:
            return None
        deadline = time.monotonic() + window_s
        bad_probes = 0
        while time.monotonic() < deadline:
            if not canary.alive():
                return "canary_died"
            try:
                h = _rpc(self.host, canary.port, {"op": "health"},
                         timeout_s=self.probe_timeout_s)
                bad_probes = 0 if "status" in h else bad_probes + 1
            except Exception:
                bad_probes += 1
            if bad_probes >= 3:
                return "canary_unhealthy"
            if self.fleet is not None:
                try:
                    if canary.idx in set(self.fleet.outliers()):
                        return "canary_outlier"
                except Exception:
                    pass
            att = self._fleet_attainment()
            if baseline is not None and att is not None \
                    and baseline - att > slo_regress:
                return "slo_regression"
            if canary_check is not None:
                why = canary_check()
                if why:
                    return str(why)
            time.sleep(min(self.probe_interval_s,
                           max(0.05, deadline - time.monotonic())))
        return None

    def _swap_replica(self, rep: Replica, checkpoint: str,
                      generation: int, timeout_s: float,
                      rollback: bool = False) -> Optional[str]:
        """One replica's hot swap over the wire; returns a typed error
        string or None on a verified success (the replica answers its
        health probe AT the target generation)."""
        payload = {"op": "swap", "checkpoint": checkpoint,
                   "generation": generation, "timeout_s": timeout_s}
        if rollback:
            payload["rollback"] = True
        try:
            reply = _rpc(self.host, rep.port, payload,
                         timeout_s=timeout_s + 30.0)
        except Exception as e:
            return f"{type(e).__name__}: {e}"
        if reply.get("error"):
            return f"{reply['error']}: {reply.get('reason')}"
        deadline = time.monotonic() + max(10.0,
                                          2 * self.probe_timeout_s)
        while time.monotonic() < deadline:
            if self._probe_generation(rep) == generation:
                rep.weight_generation = generation
                # satellite fix (r24): a verified swap is proof of
                # life — clear any crash-loop backoff the replica
                # accumulated before the roll
                rep.reset_backoff()
                return None
            time.sleep(0.1)
        return "swap_unverified: health never showed the target " \
               "generation"

    def _respawn_with_config(self, rep: Replica,
                             timeout_s: float = 60.0) -> bool:
        """Forward-convergence fallback: kill + respawn ``rep`` from
        the COMMITTED fleet config (self.checkpoint at
        self.weight_generation) and wait for a healthy probe. False
        hands the replica to the monitor's backoff/respawn path —
        which also spawns from the committed config, so the fleet
        still converges."""
        if rep.proc is not None:
            try:
                rep.proc.kill()
                rep.proc.wait(timeout=10.0)
            except Exception:
                pass
        rep.restarts += 1
        self._spawn(rep)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if not rep.alive():
                break
            try:
                h = _rpc(self.host, rep.port, {"op": "health"},
                         timeout_s=self.probe_timeout_s)
                if "status" in h:
                    rep.ready = True
                    rep.reset_backoff()
                    rep.weight_generation = self.weight_generation
                    return True
            except Exception:
                pass
            time.sleep(0.25)
        self._mark_dead(rep)
        return False

    def _handoff_before_swap(self, rep: Replica,
                             timeout_s: float) -> Optional[Dict]:
        """Hand the victim's hot chains to survivors before its swap
        invalidates them (the generation bump clears its cache). Same
        degradation contract as the r20 drain handoff: failures mean
        re-prefill-on-first-use, never a blocked roll."""
        heads: List[str] = list(rep.prefix_keys)
        try:
            h = _rpc(self.host, rep.port, {"op": "health"},
                     timeout_s=timeout_s)
            heads = list(h.get("prefix_keys") or heads)
        except Exception:
            pass
        survivors = [r for r in self.live()
                     if r.idx != rep.idx and r.role != "prefill"]
        if not heads or not survivors:
            return None
        return handoff_chains(self.host, rep.port, heads, survivors,
                              timeout_s=timeout_s)

    def _rollback_generation(self, checkpoint: Optional[str],
                             generation: int, journal,
                             reason: str,
                             swap_timeout_s: float = 120.0) -> List:
        """Converge every live replica BACK to ``generation`` (the
        canary auto-rollback sweep, also recovery's roll_incomplete
        convergence). Each rollback swap is its own journaled roll
        action with ``rollback`` marked; a replica that refuses the
        swap (or a fleet with no old checkpoint to reload) is
        respawned from the committed config instead — the fleet never
        stays mixed."""
        out = []
        for rep in sorted(self.live(), key=lambda r: r.idx):
            cur = self._probe_generation(rep)
            if cur == generation:
                continue
            seq = None
            if journal is not None:
                seq = journal.begin(
                    "roll", replica=rep.idx, checkpoint=checkpoint,
                    generation_from=(cur if cur is not None
                                     else rep.weight_generation),
                    generation_to=generation, rollback=True,
                    pid=(rep.proc.pid if rep.proc else None),
                    port=rep.port, role=rep.role, reason=reason)
            err = ("no rollback checkpoint"
                   if not checkpoint else
                   self._swap_replica(rep, checkpoint, generation,
                                      swap_timeout_s, rollback=True))
            if err is None:
                if journal is not None:
                    journal.update(seq, phase="swapped", swapped=True)
                    journal.commit(seq)
                out.append({"replica": rep.idx, "how": "swap"})
            else:
                ok = self._respawn_with_config(rep)
                if journal is not None:
                    if ok:
                        journal.commit(seq, respawned=True)
                    else:
                        journal.rollback(
                            seq, reason="rollback_respawn_pending")
                out.append({"replica": rep.idx,
                            "how": "respawn" if ok else "pending",
                            "swap_error": err})
        return out

    def roll_fleet(self, checkpoint: str,
                   generation: Optional[int] = None,
                   canary_window_s: float = 0.0,
                   slo_regress: float = 0.1,
                   canary_check=None,
                   handoff: bool = True,
                   swap_timeout_s: float = 120.0,
                   reason: str = "roll") -> Dict:
        """Rolling weight upgrade (r24 tentpole): converge the fleet,
        replica by replica behind the router, onto ``checkpoint`` at
        the next (or given) weight generation — hot-swapping live
        engines, never dropping a request (the server-side swap holds
        admission while active slots drain; queued work waits).

        Per replica: journal a ``roll`` action (begin → swapped →
        commit, the crash-recovery record), hand its hot chains to
        survivors, issue the swap op, verify the health probe reports
        the target generation. The FIRST swapped replica is the
        canary: it is watched for ``canary_window_s`` against the
        pre-roll SLO baseline / the r17 outlier detector /
        ``canary_check`` before the rest follow — a regression swaps
        everything back to the previous generation (journaled,
        counted, flight-recorded) and the roll reports the typed
        reason.

        Failure containment: a canary whose swap fails TYPED (corrupt
        checkpoint, validation refusal) aborts the roll with zero
        replicas changed — old weights keep serving fleet-wide. A
        mid-roll swap failure AFTER the canary proved the checkpoint
        converges forward by respawning the replica from the new
        committed config instead. The committed config
        (self.checkpoint / self.weight_generation) advances when the
        canary commits, so monitor respawns during the roll come up
        on the NEW weights."""
        targets = sorted(self.live(), key=lambda r: r.idx)
        if not targets:
            return {"ok": False, "refused": "no_live_replica"}
        old_ckpt, old_gen = self.checkpoint, self.weight_generation
        gen_to = (int(generation) if generation is not None
                  else old_gen + 1)
        asc = self.autoscaler
        journal = getattr(asc, "journal", None)
        baseline = self._fleet_attainment()
        report: Dict = {"ok": False, "checkpoint": checkpoint,
                        "generation_from": old_gen,
                        "generation": gen_to, "canary": None,
                        "swapped": [], "skipped": [],
                        "respawned": [], "rolled_back": [],
                        "regression": None}
        canary_done = False
        for rep in targets:
            cur = self._probe_generation(rep)
            if cur == gen_to:
                # resume idempotency: a replica already converged (a
                # crash-recovered half-roll) is skipped, not re-rolled
                report["skipped"].append(rep.idx)
                canary_done = True
                continue
            seq = None
            if journal is not None:
                seq = journal.begin(
                    "roll", replica=rep.idx, checkpoint=checkpoint,
                    generation_from=(cur if cur is not None
                                     else rep.weight_generation),
                    generation_to=gen_to,
                    pid=(rep.proc.pid if rep.proc else None),
                    port=rep.port, role=rep.role, reason=reason)
            if handoff:
                report.setdefault("handoff", {})[str(rep.idx)] = \
                    self._handoff_before_swap(rep, swap_timeout_s)
            if asc is not None:
                asc._chaos_hold()
            err = self._swap_replica(rep, checkpoint, gen_to,
                                     swap_timeout_s)
            if err is not None:
                if not canary_done:
                    # canary refusal: NOTHING changed — the corrupt/
                    # mismatched checkpoint never reaches a second
                    # replica and old weights keep serving everywhere
                    if journal is not None:
                        journal.rollback(seq,
                                         reason="canary_swap_failed")
                    report["failed"] = {"replica": rep.idx,
                                        "error": err}
                    report["refused"] = "canary_swap_failed"
                    if asc is not None:
                        asc._record("roll", "canary_swap_failed",
                                    ok=False, replica=rep.idx,
                                    generation=gen_to, seq=seq)
                    return report
                # the canary proved the checkpoint: converge forward
                ok = self._respawn_with_config(rep)
                if journal is not None:
                    if ok:
                        journal.update(seq, phase="swapped",
                                       swapped=True, respawned=True)
                        journal.commit(seq)
                    else:
                        journal.rollback(
                            seq, reason="roll_respawn_pending")
                report["respawned"].append(
                    {"replica": rep.idx, "swap_error": err,
                     "ready": ok})
                continue
            if journal is not None:
                journal.update(seq, phase="swapped", swapped=True)
                journal.commit(seq)
            report["swapped"].append(rep.idx)
            if not canary_done:
                canary_done = True
                report["canary"] = rep.idx
                # commit the new config NOW: respawns during the rest
                # of the roll must come up on the proven new weights
                self.checkpoint = checkpoint
                self.weight_generation = gen_to
                why = self._watch_canary(rep, canary_window_s,
                                         baseline, slo_regress,
                                         canary_check)
                if why is not None:
                    self.checkpoint = old_ckpt
                    self.weight_generation = old_gen
                    report["regression"] = why
                    report["rolled_back"] = \
                        self._rollback_generation(
                            old_ckpt, old_gen, journal,
                            reason=f"canary_{why}",
                            swap_timeout_s=swap_timeout_s)
                    if asc is not None:
                        asc._record("roll", f"canary_rollback_{why}",
                                    ok=False, canary=rep.idx,
                                    generation=gen_to)
                    return report
        self.checkpoint = checkpoint
        self.weight_generation = gen_to
        if journal is not None:
            journal.record_config(checkpoint, gen_to)
        if asc is not None:
            asc._record("roll", reason, ok=True, generation=gen_to,
                        swapped=len(report["swapped"]),
                        skipped=len(report["skipped"]),
                        respawned=len(report["respawned"]))
        report["ok"] = True
        return report

    @property
    def restarts_total(self) -> int:
        return sum(r.restarts for r in self.replicas)

    def live(self) -> List[Replica]:
        return [r for r in self.replicas
                if r.ready and r.alive() and not r.draining]

    # -- internals ---------------------------------------------------------

    def _spawn(self, rep: Replica) -> None:
        rep.port = _free_port(self.host)
        rep.ready = False
        rep.probe_failures = 0
        rep.next_spawn_t = None
        rep.spawn_t = time.monotonic()
        rep.close_log()
        rep.log_path = os.path.join(self.log_dir,
                                    f"replica{rep.idx}.log")
        rep._log_file = open(rep.log_path, "ab")
        # "{replica}" in an arg expands to this replica's index — how
        # per-replica paths (e.g. --spill-dir subdirs) stay disjoint
        # while every replica shares one server_args list
        extra = [a.replace("{replica}", str(rep.idx))
                 if "{replica}" in a else a for a in self.server_args]
        if rep.role != "mixed":
            extra = ["--role", rep.role] + extra
        # weight hot-swap (r24): spawn at the fleet's COMMITTED weight
        # config — a monitor respawn or a --roles re-role restart after
        # a roll boots the rolled checkpoint at the rolled generation
        # instead of regressing to the boot image at generation 0
        if self.weight_generation:
            extra = ["--weight-generation",
                     str(self.weight_generation)] + extra
        if self.checkpoint:
            extra = ["--checkpoint", self.checkpoint] + extra
        cmd = [sys.executable, "-m", "paddle_tpu.serving.server",
               "--model", self.model, "--host", self.host,
               "--port", str(rep.port)] + extra
        env = dict(os.environ)
        env.update(self.replica_env)
        if self.journal_path:
            # autoscaler fleet markers (r21): a restarted supervisor's
            # recovery (and the conftest stray guard) attributes an
            # orphaned server to its fleet by these even when the
            # journal's pid snapshot is stale (monitor respawns change
            # pids without a journal write)
            from .autoscaler import JOURNAL_ENV, REPLICA_IDX_ENV
            env[JOURNAL_ENV] = self.journal_path
            env[REPLICA_IDX_ENV] = str(rep.idx)
        rep.proc = subprocess.Popen(cmd, stdout=rep._log_file,
                                    stderr=subprocess.STDOUT, env=env)

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            # list(): the autoscaler rebinds self.replicas on attach/
            # remove — iterate one consistent snapshot per sweep
            for rep in list(self.replicas):
                if self._stop.is_set():
                    return
                if rep.draining:
                    # deliberate scale-down/rerole victim: its death
                    # is intended — respawning it (or charging
                    # _mark_dead backoff) would fight the actuator
                    continue
                if rep.proc is None or rep.next_spawn_t is not None:
                    # awaiting backoffed respawn
                    if rep.next_spawn_t is not None and \
                            time.monotonic() >= rep.next_spawn_t:
                        rep.restarts += 1
                        self._spawn(rep)
                    continue
                if not rep.alive():
                    self._mark_dead(rep)
                    continue
                probe_exc: Optional[BaseException] = None
                try:
                    h = _rpc(self.host, rep.port, {"op": "health"},
                             timeout_s=self.probe_timeout_s)
                    ok = "status" in h
                except Exception as e:
                    ok = False
                    probe_exc = e
                if ok:
                    rep.ready = True
                    rep.reset_backoff()
                    self._scrape_metrics(rep)
                    self._scrape_capacity(rep)
                    # cache-affinity advertisement (r15): best-effort —
                    # an old server build without these fields just
                    # leaves the replica unadvertised (RR/least-loaded
                    # routing still applies)
                    try:
                        rep.prefix_keys = frozenset(
                            h.get("prefix_keys") or ())
                        rep.prefix_truncated = bool(
                            h.get("prefix_keys_truncated"))
                        role = h.get("role")
                        if role in ("mixed", "prefill", "decode"):
                            rep.role = role
                        ps = h.get("page_size")
                        rep.page_size = int(ps) if ps else None
                        rep.load = (int(h.get("active") or 0)
                                    + int(h.get("queued") or 0))
                        g = h.get("weight_generation")
                        if isinstance(g, int) and \
                                not isinstance(g, bool):
                            rep.weight_generation = g
                    except (TypeError, ValueError):
                        pass
                else:
                    rep.probe_failures += 1
                    # taxonomy (r17): timeout / refused / malformed /
                    # torn are different incidents; count them apart
                    kind = classify_probe_failure(probe_exc)
                    rep.probe_failures_by_kind[kind] = \
                        rep.probe_failures_by_kind.get(kind, 0) + 1
                    rep.last_probe_error = (
                        kind if probe_exc is None else
                        f"{kind}: {type(probe_exc).__name__}: "
                        f"{probe_exc}")
                    stuck_warmup = (
                        not rep.ready and rep.spawn_t is not None
                        and time.monotonic() - rep.spawn_t
                        > self.ready_timeout_s)
                    if (rep.ready and
                            rep.probe_failures
                            >= self.max_probe_failures) or stuck_warmup:
                        # half-alive (was ready, socket went
                        # unresponsive) OR wedged during startup (alive
                        # but never answered a probe within
                        # ready_timeout_s — e.g. a hung compile). Both
                        # are permanent capacity loss unless the
                        # supervisor reclaims them: kill and let the
                        # respawn path own recovery
                        try:
                            rep.proc.kill()
                        except OSError:
                            pass
                        self._mark_dead(rep)
            self._stop.wait(timeout=self.probe_interval_s)

    def _scrape_metrics(self, rep: Replica) -> None:
        """Collector half of the probe cycle (r17): pull the replica's
        structured metrics export into the fleet plane. A scrape that
        fails mid-cycle (replica died between probe and scrape, torn
        reply) marks the replica STALE — its last export is kept for
        postmortems but dropped from fleet rollups, so a dying replica
        can never poison fleet totals."""
        if self.fleet is None or not self.collect_metrics:
            return
        try:
            reply = _rpc(self.host, rep.port, {"op": "export"},
                         timeout_s=self.probe_timeout_s)
            export = reply.get("export")
            if not isinstance(export, dict):
                raise ValueError("export op returned no export dict")
            self.fleet.ingest(rep.idx, export)
        except Exception:
            self.fleet.mark_stale(rep.idx)

    def _scrape_capacity(self, rep: Replica) -> None:
        """Memory observatory (r18): pull the replica's ``capacity``
        op (occupancy by owner class + exhaustion forecast) each
        healthy probe cycle. Advisory — a failed scrape just leaves
        the last snapshot to age out of ``fleet_capacity`` rollups."""
        if not self.collect_metrics:
            return
        try:
            reply = _rpc(self.host, rep.port, {"op": "capacity"},
                         timeout_s=self.probe_timeout_s)
            if not isinstance(reply.get("num_pages"), int):
                raise ValueError("capacity op returned no pool size")
            rep.capacity = reply
            rep.capacity_t = time.monotonic()
        except Exception:
            pass

    def fleet_capacity(self) -> Dict:
        """The ``fleet_capacity`` payload (r18): per-replica occupancy
        merged into one fleet view — summed owner-class page counts,
        the fleet used-fraction, and the most urgent (minimum)
        time-to-exhaustion forecast across replicas. Stale snapshots
        (older than 4 probe intervals, min 10 s — the collector's
        freshness rule) are reported but excluded from the rollup."""
        now = time.monotonic()
        stale_after = max(10.0, 4 * self.probe_interval_s)
        totals: Dict[str, int] = {}
        num_pages = 0
        fresh = 0
        ttes: List[float] = []
        per: Dict[str, Dict] = {}
        for r in self.replicas:
            cap = r.capacity
            is_fresh = (cap is not None and r.ready
                        and now - r.capacity_t <= stale_after)
            per[str(r.idx)] = {
                "fresh": is_fresh,
                "age_s": (round(now - r.capacity_t, 3)
                          if cap is not None else None),
                "capacity": cap}
            if not is_fresh:
                continue
            fresh += 1
            num_pages += int(cap.get("num_pages") or 0)
            for k, v in (cap.get("occupancy") or {}).items():
                totals[k] = totals.get(k, 0) + int(v)
            tte = (cap.get("forecast") or {}).get("tte_s")
            if isinstance(tte, (int, float)):
                ttes.append(float(tte))
        return {"replicas_fresh": fresh,
                "replicas_known": len(self.replicas),
                "num_pages": num_pages,
                "occupancy": totals,
                "used_fraction": (
                    round(1.0 - totals.get("free", 0) / num_pages, 4)
                    if num_pages else None),
                # the fleet exhausts when its FIRST replica does: a
                # router can't split one request across pools
                "tte_s": (round(min(ttes), 3) if ttes else None),
                "per_replica": per}

    def fleet_stats(self) -> Dict:
        """The ``fleet_stats`` payload (r17): the collector's merged
        telemetry (bucket-exact fleet histograms, merged SLO window,
        pressure verdict, outlier flags) JOINED with the supervision
        state only this process knows — per-replica probe-failure
        taxonomy, restart counts, and live backoff gates (previously
        computed and exported nowhere)."""
        now = time.monotonic()
        supervision = {}
        for r in self.replicas:
            supervision[str(r.idx)] = {
                "port": r.port, "ready": r.ready, "alive": r.alive(),
                "load": r.load,
                "role": getattr(r, "role", "mixed"),
                "draining": r.draining,
                "weight_generation": getattr(r, "weight_generation",
                                             0),
                "restarts": r.restarts,
                "consec_deaths": r.consec_deaths,
                "probe_failures": r.probe_failures,
                "probe_failures_by_kind":
                    dict(r.probe_failures_by_kind),
                "last_probe_error": r.last_probe_error,
                "backoff_remaining_s": (
                    None if r.next_spawn_t is None
                    else round(max(0.0, r.next_spawn_t - now), 3)),
            }
        out = (self.fleet.fleet_snapshot()
               if self.fleet is not None else
               {"replicas_fresh": 0, "replicas_known": 0,
                "collector": None})
        out["supervision"] = supervision
        out["restarts_total"] = self.restarts_total
        out["collect_metrics"] = self.collect_metrics
        # weight hot-swap (r24): the committed fleet generation plus
        # the set actually OBSERVED on live replicas — more than one
        # entry means a roll is in flight (or went wrong); the chaos
        # harness asserts this converges to exactly one
        out["weight_generation"] = self.weight_generation
        out["weight_generations"] = sorted(
            {getattr(r, "weight_generation", 0)
             for r in self.live()} or {self.weight_generation})
        # actuator state (r21): envelope, cooldown-remaining, last
        # action, journal health — fleet_stats is the one op an
        # operator watches, so the autoscaler reports through it
        if self.autoscaler is not None:
            out["autoscaler"] = self.autoscaler.status()
        return out

    def _mark_dead(self, rep: Replica) -> None:
        rep.ready = False
        rep.consec_deaths += 1
        backoff = min(self.backoff_max_s,
                      self.backoff_base_s
                      * 2 ** (rep.consec_deaths - 1))
        rep.next_spawn_t = time.monotonic() + backoff
        rep.close_log()
        if self.fleet is not None:
            # drop the dead replica from fleet rollups immediately —
            # not after stale_after_s ages it out
            self.fleet.mark_stale(rep.idx)


def rendezvous_owner(key_hex: str, candidates):
    """Highest-random-weight owner of a chain key among ``candidates``
    (objects with ``.idx``) — the SAME formula the router's affinity
    rendezvous uses, so chains handed off at drain time land exactly
    where future keyed requests will be steered."""
    return max(candidates, key=lambda r: hashlib.blake2b(
        f"{key_hex}:{r.idx}".encode(), digest_size=8).digest())


def handoff_chains(host: str, victim_port: int,
                   heads: Sequence[str], survivors,
                   timeout_s: float = 30.0) -> Dict:
    """Prefix-affinity-aware drain handoff (r20, ROADMAP 3(a)): ask
    each survivor to ``prefetch`` its rendezvous share of the victim's
    advertised chain heads straight from the victim (the blobs never
    transit this process). ``survivors`` are objects with ``.idx`` and
    ``.port``. Per-head failures are recorded, never raised — a failed
    handoff just means the chain is re-prefilled on first use, the
    same typed fallback as every other fetch path."""
    report: Dict = {"heads": len(heads), "imported_pages": 0,
                    "bytes": 0, "failures": [], "per_survivor": {}}
    if not heads or not survivors:
        return report
    assign: Dict[int, List[str]] = {}
    by_idx = {r.idx: r for r in survivors}
    for head in heads:
        assign.setdefault(rendezvous_owner(head, survivors).idx,
                          []).append(head)
    for idx, share in assign.items():
        rep = by_idx[idx]
        try:
            reply = _rpc(host, rep.port,
                         {"op": "prefetch", "host": host,
                          "port": victim_port, "heads": share},
                         timeout_s=timeout_s)
        except Exception as e:
            report["failures"].append(
                f"survivor {idx}: {type(e).__name__}: {e}")
            continue
        if reply.get("error"):
            report["failures"].append(
                f"survivor {idx}: {reply['error']}: "
                f"{reply.get('reason')}")
            continue
        report["imported_pages"] += int(reply.get("imported") or 0)
        report["bytes"] += int(reply.get("bytes") or 0)
        report["per_survivor"][str(idx)] = {
            "heads": len(share),
            "imported": int(reply.get("imported") or 0),
            "corrupt": int(reply.get("corrupt") or 0),
            "skipped": int(reply.get("skipped") or 0)}
    return report


class _BackendLost(ConnectionError):
    """Router-internal: the backend replica died mid-request."""


class _ClientLost(ConnectionError):
    """Router-internal: the ROUTER'S OWN client socket died mid-relay.
    Must never be confused with `_BackendLost`: failing over would burn
    healthy replicas generating into a dead socket and corrupt the
    replica-failure metrics."""


class FailoverRouter:
    """One client-facing port over N supervised replicas.

    Per-request routing: round-robin over ready replicas — except
    KEYED requests, which are steered for CACHE AFFINITY (r15): the
    prompt's first-block prefix key (the same chained blake2b the
    prefix cache uses) is matched against each replica's advertised
    cached keys; an advertising holder wins, otherwise a rendezvous
    hash over the live replicas picks a stable owner so repeated
    prefixes concentrate on one replica and BUILD affinity, and when
    no key can be computed (short prompt, no advertisement yet) the
    least-loaded live replica takes it. Affinity is a ROUTING HINT
    only: excluded/dead replicas are always filtered first, so it can
    never block failover — a steered request whose replica dies fails
    over exactly like any other.

    A backend that dies mid-request (connection error, or an armed
    ``net.recv`` schedule) costs an unkeyed request a typed retryable
    ``ReplicaFailed``; a KEYED request is resubmitted to another live
    replica, with already-relayed streamed tokens suppressed from the
    resubmission (greedy determinism makes the resubmitted stream a
    superset-in-order of what was already sent). ``health`` is
    answered by the router itself with per-replica state; other admin
    ops go to the first live replica."""

    def __init__(self, supervisor: Supervisor, host: str = "127.0.0.1",
                 port: int = 0, max_failover: int = 3,
                 backend_timeout_s: float = 300.0,
                 no_replica_wait_s: float = 60.0,
                 affinity: bool = True,
                 trace_sample: float = 0.0, tracer=None,
                 deprioritize_outliers: bool = False,
                 disaggregate: bool = True,
                 fleet_cache: bool = True,
                 forecast_placement: bool = False):
        self.sup = supervisor
        # back-reference (r21): the autoscaler's shape planner reads
        # handoff_prefill_failures_total off the router; duck-typed —
        # a frozen stub supervisor just doesn't get one
        try:
            supervisor.router = self
        except AttributeError:
            pass
        self.host = host
        self._requested_port = port
        self.max_failover = int(max_failover)
        self.backend_timeout_s = float(backend_timeout_s)
        self.no_replica_wait_s = float(no_replica_wait_s)
        self.affinity = bool(affinity)
        # disaggregated prefill/decode (r20), default ON but inert on
        # an all-mixed fleet (byte-for-byte the pre-r20 routing): with
        # prefill-class AND decode-capable replicas live, a keyed
        # request with a computable first-block key routes
        # PREFILL-FIRST — the prompt runs as a prefill_only job on a
        # prefill replica (rendezvous-stable so residency builds),
        # then the request is dispatched to a decode-capable replica
        # with a fetch_from hint naming the prefill peer; the decode
        # side pulls the chain over fetch_pages and splices it instead
        # of re-prefilling. Every handoff failure degrades to local
        # prefill, never a hang.
        self.disaggregate = bool(disaggregate)
        # fleet telemetry (r17), default OFF: steer UNKEYED traffic
        # away from replicas the outlier detector currently flags
        # (slow step-ms/TPOT or erroring vs the fleet median). A
        # routing PREFERENCE only — flagged replicas still serve when
        # they are all that's live, keyed/affinity routing is
        # untouched, and failover exclusion always filters first.
        self.deprioritize_outliers = bool(deprioritize_outliers)
        # fleet cache (r23), default ON and inert without advertised
        # keys: when the picked replica does NOT advertise a keyed
        # request's chain but some OTHER live replica does, attach a
        # fetch_from hint naming that peer — any replica's tiers are
        # the fleet's cache, not just the designated prefill owner's.
        # A dead/evicted peer degrades exactly like the r20 handoff:
        # typed PageFetchFailed, counted, local prefill, same tokens.
        self.fleet_cache = bool(fleet_cache)
        # byte-planning placement (r23), default OFF: prefer replicas
        # whose capacity forecast (r18 exhaustion EWMA, scraped by the
        # supervisor's capacity probe) is NOT about to exhaust. A
        # PREFERENCE like deprioritize_outliers — never filters to
        # empty, failover exclusion still applies first.
        self.forecast_placement = bool(forecast_placement)
        # end-to-end tracing (r16): the router is the FIRST hop, so
        # its sampler decides for the whole request — a sampled
        # request's forward carries a trace context that forces the
        # replica to trace under the router's forward span (one trace
        # id, one merged tree; keyed failover appends failover spans
        # to the same tree)
        if tracer is not None:
            self.tracer = tracer
        else:
            from .tracing import SpanTracer, stderr_span_sink
            rate, sink = float(trace_sample), None
            if os.environ.get("PT_SERVING_DEBUG"):
                rate, sink = 1.0, stderr_span_sink
            self.tracer = SpanTracer(sample_rate=rate, on_span=sink)
        self.port: Optional[int] = None
        self.failovers_total = 0
        self.replica_failures_total = 0
        # cache-affinity accounting (r15): per PICK (routing decision),
        # not per request — a failover retry that re-picks counts
        # again. routed = picks that had a computable first-block key;
        # hits = picks steered to a replica ADVERTISING the key (vs
        # rendezvous-hash placement). Guarded by _lock: picks run on
        # concurrent connection threads.
        self.affinity_routed_total = 0
        self.affinity_hits_total = 0
        # disaggregation accounting (r20): handoffs_total counts
        # requests dispatched with a fetch_from hint (prefill hop run
        # or chain already parked on a prefill replica);
        # handoff_prefill_failures_total counts prefill hops that
        # failed and fell back to plain dispatch (local prefill)
        self.handoffs_total = 0
        self.handoff_prefill_failures_total = 0
        # fleet-cache accounting (r23): picks where the hint named a
        # non-owner peer advertising the chain (the any-replica lane)
        self.fleet_cache_hints_total = 0
        # byte-planning placement accounting (r23)
        self.forecast_steers_total = 0
        # optional routing-event hook: trace({"t": ..., "ev": ...,
        # ...}) — the chaos harness uses it for postmortems
        self.trace = None
        self._rr = 0
        self._stopping = False
        self._sock: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self._lock = threading.Lock()

    def start(self) -> int:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self._requested_port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="pt-router-accept")
        t.start()
        self._threads.append(t)
        return self.port

    def stop(self) -> None:
        self._stopping = True
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        for t in list(self._threads):
            if t is not threading.current_thread():
                t.join(timeout=5.0)

    def __enter__(self) -> "FailoverRouter":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- internals ---------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                self._sock.settimeout(0.2)
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True, name="pt-router-conn")
            with self._lock:
                self._threads = [x for x in self._threads
                                 if x.is_alive()]
                self._threads.append(t)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        rfile = conn.makefile("r", encoding="utf-8")
        wfile = conn.makefile("w", encoding="utf-8")

        def send(obj: Dict) -> None:
            wfile.write(json.dumps(obj) + "\n")
            wfile.flush()

        try:
            for line in rfile:
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError as e:
                    send({"error": "BadRequest", "reason": str(e)})
                    continue
                try:
                    self._handle(msg, send)
                except Exception as e:  # typed reply, never a hang
                    send({"error": type(e).__name__, "reason": str(e)})
        except (OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, msg: Dict, send) -> None:
        op = msg.get("op", "generate")
        if op == "health":
            send({"status": "ok" if self.sup.live() else "degraded",
                  "live": len(self.sup.live()),
                  "failovers_total": self.failovers_total,
                  "affinity_routed_total": self.affinity_routed_total,
                  "affinity_hits_total": self.affinity_hits_total,
                  "disaggregate": self.disaggregate,
                  "handoffs_total": self.handoffs_total,
                  "handoff_prefill_failures_total":
                      self.handoff_prefill_failures_total,
                  "fleet_cache_hints_total":
                      self.fleet_cache_hints_total,
                  "forecast_steers_total": self.forecast_steers_total,
                  "replicas": [{"idx": r.idx, "port": r.port,
                                "ready": r.ready, "alive": r.alive(),
                                "restarts": r.restarts,
                                "role": getattr(r, "role", "mixed"),
                                "load": getattr(r, "load", 0),
                                "advertised_prefixes":
                                    len(getattr(r, "prefix_keys", ())),
                                "prefix_keys_truncated":
                                    getattr(r, "prefix_truncated",
                                            False)}
                               for r in self.sup.replicas]})
            return
        if op == "trace":
            # the ROUTER's share of the span trees (pick/forward/
            # failover spans); replica shares come from each replica's
            # own trace op and merge by trace id — router spans carry
            # the forward span ids the replica roots reference as
            # remote_parent
            send({"traces": self.tracer.finished(),
                  "events": self.tracer.events(),
                  "sample_rate": self.tracer.sample_rate})
            return
        if op == "fleet_stats":
            # fleet telemetry plane (r17): the collector's merged view
            # + supervision taxonomy, answered BY THE ROUTER (the one
            # port an operator watches). Duck-typed: a stub supervisor
            # without the plane gets a typed reply, not a crash.
            fs = getattr(self.sup, "fleet_stats", None)
            if fs is None:
                send({"error": "FleetMetricsUnavailable",
                      "reason": "supervisor has no fleet telemetry "
                                "plane"})
                return
            stats = fs()
            stats["router"] = {
                "failovers_total": self.failovers_total,
                "replica_failures_total": self.replica_failures_total,
                "affinity_routed_total": self.affinity_routed_total,
                "affinity_hits_total": self.affinity_hits_total,
                "deprioritize_outliers": self.deprioritize_outliers,
                "disaggregate": self.disaggregate,
                "handoffs_total": self.handoffs_total,
                "handoff_prefill_failures_total":
                    self.handoff_prefill_failures_total,
                "fleet_cache_hints_total": self.fleet_cache_hints_total,
                "forecast_steers_total": self.forecast_steers_total,
            }
            send({"fleet": stats})
            return
        if op == "fleet_capacity":
            # memory observatory (r18): merged per-replica occupancy +
            # the fleet's nearest time-to-exhaustion — the capacity
            # half of the autoscaler input contract (3a). Duck-typed
            # like fleet_stats.
            fc = getattr(self.sup, "fleet_capacity", None)
            if fc is None:
                send({"error": "FleetCapacityUnavailable",
                      "reason": "supervisor has no capacity "
                                "collector"})
                return
            send({"capacity": fc()})
            return
        if op == "fleet_metrics":
            # fleet Prometheus exposition: per-replica series carry a
            # replica label, fleet rollups live in fleet_* families
            fm = getattr(self.sup, "fleet", None)
            if fm is None:
                send({"error": "FleetMetricsUnavailable",
                      "reason": "supervisor has no fleet telemetry "
                                "plane"})
                return
            text = fm.prometheus_text()
            asc = getattr(self.sup, "autoscaler", None)
            if asc is not None:
                # r21 families: serving_autoscale_actions_total +
                # serving_fleet_replicas ride the same exposition
                text = (text.rstrip("\n") + "\n"
                        + "\n".join(asc.prometheus_lines()) + "\n")
            send({"text": text})
            return
        if op == "autoscale":
            # actuator surface (r21): status, plus FORCED actions
            # (cooldown bypassed, envelope/guards still enforced) —
            # the chaos harness and operators drive deterministic
            # scale events through the one client-facing port
            asc = getattr(self.sup, "autoscaler", None)
            if asc is None:
                send({"error": "AutoscalerUnavailable",
                      "reason": "supervisor started without "
                                "--autoscale"})
                return
            action = msg.get("action")
            if action in (None, "status"):
                send({"autoscaler": asc.status()})
            elif action == "scale_up":
                send({"result": asc.scale_up(
                    reason=msg.get("reason") or "forced",
                    role=msg.get("role") or "mixed", force=True)})
            elif action == "scale_down":
                send({"result": asc.scale_down(
                    reason=msg.get("reason") or "forced",
                    force=True)})
            elif action == "rerole":
                send({"result": asc.rerole(
                    int(msg.get("replica", -1)),
                    msg.get("role") or "mixed",
                    reason=msg.get("reason") or "forced",
                    force=True)})
            else:
                send({"error": "BadRequest",
                      "reason": f"unknown autoscale action "
                                f"{action!r}"})
            return
        if op == "roll":
            # rolling weight upgrade (r24): the one-port drive for
            # Supervisor.roll_fleet — blocks this connection thread
            # for the roll's duration (other connections keep
            # routing). Duck-typed like the other fleet ops.
            rf = getattr(self.sup, "roll_fleet", None)
            if rf is None:
                send({"error": "RollUnavailable",
                      "reason": "supervisor has no roll_fleet"})
                return
            ckpt = msg.get("checkpoint")
            if not isinstance(ckpt, str) or not ckpt:
                send({"error": "BadRequest",
                      "reason": "roll needs a 'checkpoint' directory"})
                return
            kwargs: Dict = {}
            if msg.get("generation") is not None:
                kwargs["generation"] = int(msg["generation"])
            if msg.get("canary_window_s") is not None:
                kwargs["canary_window_s"] = \
                    float(msg["canary_window_s"])
            if msg.get("slo_regress") is not None:
                kwargs["slo_regress"] = float(msg["slo_regress"])
            send({"roll": rf(ckpt, **kwargs)})
            return
        if op != "generate":
            # admin op: first live replica answers (replica-targeted
            # audits talk to replica ports directly)
            rep = self._pick(set())
            if rep is None:
                send({"error": "NoReplicaAvailable", "retryable": True})
                return
            try:
                send(_rpc(self.sup.host, rep.port, msg,
                          timeout_s=self.backend_timeout_s))
            except Exception as e:
                send({"error": "ReplicaFailed", "retryable": True,
                      "reason": f"{type(e).__name__}: {e}"})
            return
        self._route_generate(msg, send)

    def _affinity_key(self, msg: Dict) -> Optional[str]:
        """The prompt's first-block prefix key (hex) — the unit the
        prefix cache shares by and replicas advertise. None when it
        cannot be computed: unkeyed request, no live replica has
        reported its page size yet, or the prompt has no full
        shareable first block (length <= page_size: the cache never
        shares a block covering the last prompt token)."""
        if not self.affinity or msg.get("key") is None:
            return None
        prompt = msg.get("prompt")
        if not isinstance(prompt, list) or not prompt:
            return None
        # getattr: the supervisor is duck-typed (tests front plain
        # stub replicas) — a replica without advertisement fields
        # simply never attracts affinity routing
        ps = next((getattr(r, "page_size", None)
                   for r in self.sup.live()
                   if getattr(r, "page_size", None)), None)
        if not ps or len(prompt) <= ps:
            return None
        from .prefix_cache import _block_hash
        try:
            # generation-aware (r24): replicas salt their chain roots
            # with their weight generation, so the router must hash
            # with the fleet's COMMITTED generation or no advertised
            # key would ever match after a roll. Mid-roll, replicas
            # still on the old generation simply stop matching and
            # degrade to rendezvous placement — the documented
            # cold-cache cost of a rolling upgrade.
            gen = getattr(self.sup, "weight_generation", 0) or 0
            return _block_hash(None, np.asarray(prompt[:ps],
                                                np.int32),
                               generation=gen).hex()
        except (TypeError, ValueError, OverflowError):
            return None  # malformed prompt: backend answers BadRequest

    # forecast pressure floor (r23): a replica whose fresh capacity
    # forecast projects pool exhaustion within this many seconds is
    # deprioritized by forecast_placement picks
    FORECAST_TTE_FLOOR_S = 5.0

    def _forecast_pressed(self, rep: Replica) -> bool:
        """True when ``rep``'s capacity snapshot is FRESH (the r18
        collector freshness rule) and its exhaustion forecast projects
        the pool empty within FORECAST_TTE_FLOOR_S."""
        cap = getattr(rep, "capacity", None)
        if not isinstance(cap, dict):
            return False
        stale_after = max(10.0, 4 * getattr(self.sup,
                                            "probe_interval_s", 2.5))
        if time.monotonic() - getattr(rep, "capacity_t", 0.0) \
                > stale_after:
            return False
        tte = (cap.get("forecast") or {}).get("tte_s")
        return (isinstance(tte, (int, float))
                and float(tte) < self.FORECAST_TTE_FLOOR_S)

    def _fleet_cache_hint(self, rep: Replica,
                          affinity_key: Optional[str],
                          trace=None) -> Optional[Dict]:
        """Fleet cache (r23): the pick did NOT land on a holder (none
        live in the pickable set, or the holder died and is excluded)
        — but ANY live peer advertising the chain can serve it over
        fetch_pages, prefill-class or not: every replica's spill tiers
        are one fleet-wide KV byte cache. Returns a fetch_from hint
        naming the least-loaded advertising peer, or None (lane off,
        unkeyed, the pick already holds the chain, or no peer
        advertises it). If the peer dies before the pull, the decode
        side's typed PageFetchFailed falls back to local prefill —
        never a hang, never wrong tokens."""
        if not self.fleet_cache or affinity_key is None:
            return None
        if affinity_key in getattr(rep, "prefix_keys", ()):
            return None  # already resident where decode will run
        peers = [r for r in self.sup.live()
                 if r.idx != rep.idx
                 and affinity_key in getattr(r, "prefix_keys", ())]
        if not peers:
            return None
        peer = min(peers, key=lambda r: (getattr(r, "load", 0), r.idx))
        with self._lock:
            self.fleet_cache_hints_total += 1
        if trace is not None:
            trace("fleet_cache_hint", rep=rep.idx, peer=peer.idx)
        return {"host": self.sup.host, "port": peer.port}

    def _pick(self, exclude: set, affinity_key: Optional[str] = None,
              keyed: bool = False,
              exclude_prefill: bool = False) -> Optional[Replica]:
        """Pick a live replica outside ``exclude``. With an
        ``affinity_key``: an ADVERTISING holder wins (ties:
        least-loaded), else a rendezvous hash over the live set picks
        a stable owner so repeated prefixes build cache residency on
        one replica. A KEYED request whose affinity key could not be
        computed (short prompt, no advertised page size) falls back to
        least-loaded (round-robin among load ties); unkeyed requests
        keep the pre-r15 round-robin. Liveness/exclusion filter FIRST
        — affinity is a preference among survivors and can never block
        failover. ``exclude_prefill`` (r20 role-aware dispatch) keeps
        decode streams off prefill-class replicas — they would answer
        WrongRole."""
        live = [r for r in self.sup.live() if r.idx not in exclude]
        if exclude_prefill:
            live = [r for r in live
                    if getattr(r, "role", "mixed") != "prefill"]
        if not live:
            return None
        if self.forecast_placement and len(live) > 1:
            # byte-planning placement (r23, default off): drop replicas
            # whose FRESH capacity forecast says the pool exhausts
            # within the pressure floor — a request landed there would
            # thrash evictions the moment it started decoding. A
            # preference, never a filter-to-empty; stale/absent
            # forecasts count as healthy (advisory plane, r18 rules).
            healthy = [r for r in live if not self._forecast_pressed(r)]
            if healthy and len(healthy) < len(live):
                with self._lock:
                    self.forecast_steers_total += 1
                live = healthy
        if affinity_key is not None:
            holders = [r for r in live
                       if affinity_key in getattr(r, "prefix_keys", ())]
            with self._lock:
                self.affinity_routed_total += 1
                if holders:
                    self.affinity_hits_total += 1
            if holders:
                return min(holders,
                           key=lambda r: (getattr(r, "load", 0), r.idx))
            # rendezvous (highest-random-weight) hashing: stable under
            # replica churn — removing one replica only remaps ITS
            # keys, so the rest of the fleet's cache residency survives
            return max(live, key=lambda r: hashlib.blake2b(
                f"{affinity_key}:{r.idx}".encode(),
                digest_size=8).digest())
        if keyed:
            lo = min(getattr(r, "load", 0) for r in live)
            live = [r for r in live if getattr(r, "load", 0) == lo]
        elif self.deprioritize_outliers:
            # r17 (default off): unkeyed traffic prefers replicas the
            # fleet outlier detector hasn't flagged — a preference,
            # never a filter-to-empty (a fully-flagged fleet still
            # serves), applied AFTER liveness/exclusion so it cannot
            # block failover
            fm = getattr(self.sup, "fleet", None)
            if fm is not None:
                try:
                    flagged = set(fm.outliers())
                except Exception:
                    flagged = set()
                healthy = [r for r in live if r.idx not in flagged]
                if healthy:
                    live = healthy
        with self._lock:
            self._rr += 1
            return live[self._rr % len(live)]

    def _route_generate(self, msg: Dict, send) -> None:
        keyed = msg.get("key") is not None
        # cache-affinity steering (r15): computed ONCE per request and
        # reused across failover attempts — the tried-set exclusion in
        # _pick keeps a dead affinity target from ever being retried
        affinity_key = self._affinity_key(msg)
        # token messages already sent to the client — MUTABLE so a
        # _BackendLost raised mid-stream still preserves the relay
        # progress the next attempt must suppress
        progress = {"relayed": 0}
        attempts = 0
        tried: set = set()
        arrival = time.monotonic()
        wait_deadline = arrival + self.no_replica_wait_s
        # deadline_ms is a budget FROM ARRIVAL covering the whole
        # request: each forward (first try included — time can pass
        # waiting for a live replica) carries only the REMAINING
        # budget, or a failed-over request would restart its clock on
        # every replica and overshoot the contract by up to
        # max_failover * deadline_ms
        budget_ms = msg.get("deadline_ms")
        if isinstance(budget_ms, bool) or \
                not isinstance(budget_ms, (int, float)):
            budget_ms = None  # malformed: backend answers BadRequest
        # end-to-end tracing (r16): the router's span tree for this
        # request — pick/forward/failover. A client-supplied trace
        # context is adopted; otherwise the router's sampler decides.
        prompt = msg.get("prompt")
        rtr = self.tracer.start(
            "route", ctx=msg.get("trace") if isinstance(
                msg.get("trace"), dict) else None,
            key=msg.get("key"),
            prompt_len=len(prompt) if isinstance(prompt, list) else 0)

        def trace(ev: str, **kw) -> None:
            if self.trace is not None:
                kw.update(ev=ev, key=msg.get("key"),
                          t=round(time.monotonic(), 3))
                try:
                    self.trace(kw)
                except Exception:
                    pass

        # disaggregated dispatch (r20): keyed requests with a
        # computable first-block key route PREFILL-FIRST when the
        # fleet has prefill-class replicas; the returned hint makes
        # the decode-capable target fetch the chain instead of
        # re-prefilling. None = plain dispatch (all-mixed fleet,
        # chain already decode-resident, or the hop failed — counted).
        handoff_hint = None
        if self.disaggregate and keyed and affinity_key is not None:
            handoff_hint = self._plan_handoff(msg, affinity_key, rtr,
                                              trace, budget_ms, arrival)
        while True:
            # affinity=False restores the pre-r15 keyed routing wholly
            # (round-robin, no least-loaded filter) — the bisect
            # escape hatch MIGRATION.md documents
            rep = self._pick(tried, affinity_key=affinity_key,
                             keyed=keyed and self.affinity,
                             exclude_prefill=self.disaggregate)
            trace("pick", rep=None if rep is None else rep.idx,
                  attempts=attempts)
            if rep is None:
                # every replica tried/dead: wait for the supervisor to
                # resurrect one (fresh respawns are fair game again)
                if time.monotonic() >= wait_deadline:
                    self.replica_failures_total += 1
                    if rtr is not None:
                        self.tracer.finish(rtr, state="no_replica")
                    send({"error": "NoReplicaAvailable",
                          "retryable": True,
                          "reason": "no live replica within "
                                    f"{self.no_replica_wait_s}s"})
                    return
                tried.clear()
                time.sleep(0.2)
                continue
            hint = handoff_hint
            if hint is None:
                hint = self._fleet_cache_hint(rep, affinity_key, trace)
            fwd = msg
            if hint is not None:
                # the hint survives failover: if the advertising peer
                # died meanwhile, the decode side's fetch fails typed
                # and falls back to local prefill — never a hang
                fwd = dict(msg)
                fwd["fetch_from"] = hint
            if budget_ms is not None and budget_ms > 0:
                remaining = budget_ms \
                    - (time.monotonic() - arrival) * 1e3
                if remaining <= 0:
                    if rtr is not None:
                        self.tracer.finish(rtr, state="deadline")
                    send({"error": "DeadlineExceeded",
                          "reason": "deadline_ms elapsed before "
                                    "completion",
                          "tokens_out": progress["relayed"]})
                    return
                fwd = dict(fwd)  # preserve any fetch_from hint
                fwd["deadline_ms"] = remaining
            fs = None
            if rtr is not None:
                # each forward attempt is one span; the replica roots
                # its share of the tree under this span via the wire
                # context (engine submit trace_ctx -> remote_parent)
                fs = rtr.begin("forward", parent=rtr.anchor,
                               replica=rep.idx, attempt=attempts)
                if fwd is msg:
                    fwd = dict(msg)
                fwd["trace"] = rtr.ctx(parent=fs)
            try:
                self._forward(rep, fwd, send, progress)
                trace("done", rep=rep.idx,
                      relayed=progress["relayed"])
                if rtr is not None:
                    rtr.end(fs, relayed=progress["relayed"])
                    self.tracer.finish(rtr, state="done")
                return
            except _ClientLost as e:
                # OUR client hung up mid-relay; the replica is fine.
                # Abort quietly — no failover, no replica-failure
                # metrics, nothing left to deliver the reply to.
                trace("client_lost", rep=rep.idx, err=str(e))
                if rtr is not None:
                    rtr.end(fs, error="client_lost")
                    self.tracer.finish(rtr, state="client_lost")
                return
            except _BackendLost as e:
                trace("backend_lost", rep=rep.idx, err=str(e))
                if rtr is not None:
                    rtr.end(fs, error=str(e),
                            relayed=progress["relayed"])
                attempts += 1
                tried.add(rep.idx)
                if not keyed:
                    self.replica_failures_total += 1
                    if rtr is not None:
                        self.tracer.finish(rtr, state="replica_failed")
                    send({"error": "ReplicaFailed", "retryable": True,
                          "reason": f"replica {rep.idx} lost "
                                    f"mid-request ({e}); resubmit "
                                    f"with a 'key' for transparent "
                                    f"failover"})
                    return
                if attempts > self.max_failover:
                    self.replica_failures_total += 1
                    if rtr is not None:
                        self.tracer.finish(rtr, state="replica_failed")
                    send({"error": "ReplicaFailed", "retryable": True,
                          "reason": f"{attempts} replicas lost "
                                    f"mid-request"})
                    return
                self.failovers_total += 1
                if rtr is not None:
                    # the stitch marker: the same tree continues on
                    # the next replica
                    rtr.event("failover", parent=rtr.anchor,
                              from_replica=rep.idx, attempt=attempts)

    def _plan_handoff(self, msg: Dict, affinity_key: str, rtr,
                      trace, budget_ms=None,
                      arrival: float = 0.0) -> Optional[Dict]:
        """Decide and (when needed) EXECUTE the prefill half of a
        disaggregated dispatch (r20). Returns a ``fetch_from`` hint
        for the decode forward, or None for plain dispatch:

        - no prefill-class or no decode-capable replica live → None
          (an all-mixed fleet is byte-for-byte pre-r20);
        - a decode-capable replica already advertises the chain →
          None (the affinity pick will land there; nothing to ship);
        - a prefill replica advertises it → hint at that replica,
          skipping the prefill hop entirely;
        - otherwise run the prompt as a ``prefill_only`` job on the
          rendezvous-stable prefill replica (so residency builds on
          one peer) and hint at it. A failed/typed-error hop is
          counted and degrades to plain dispatch — local prefill on
          the decode side, bit-identical output, never a hang.

        Truncation-awareness: a prefill replica advertising a
        TRUNCATED key list may hold the chain unadvertised; the
        rendezvous owner is exactly where earlier traffic parked it,
        and its own prefix cache dedupes the prefill_only job into a
        cache hit — so the hop is cheap precisely when the
        advertisement lied by omission."""
        live = self.sup.live()
        prefills = [r for r in live
                    if getattr(r, "role", "mixed") == "prefill"]
        decodes = [r for r in live
                   if getattr(r, "role", "mixed") != "prefill"]
        if not prefills or not decodes:
            return None
        if any(affinity_key in getattr(r, "prefix_keys", ())
               for r in decodes):
            return None  # already resident where decode will run
        holder = next((r for r in prefills
                       if affinity_key in getattr(r, "prefix_keys",
                                                  ())), None)
        if holder is not None:
            with self._lock:
                self.handoffs_total += 1
            trace("handoff_hint", rep=holder.idx, prefilled=False)
            return {"host": self.sup.host, "port": holder.port}
        target = rendezvous_owner(affinity_key, prefills)
        pf = {"op": "generate", "prompt": msg.get("prompt"),
              "max_new_tokens": 1, "prefill_only": True}
        for k in ("eos", "priority", "key"):
            if msg.get(k) is not None:
                pf[k] = msg[k]
        # the hop spends from the SAME deadline budget as the dispatch
        # it precedes: forward the remaining ms (the prefill replica's
        # own deadline gate sheds a hopeless job instead of queueing
        # it) and bound the RPC wait by it — a request that cannot
        # afford the hop goes straight to plain dispatch, so
        # disaggregation never makes a deadline-feasible request fail
        timeout_s = self.backend_timeout_s
        if budget_ms is not None and budget_ms > 0:
            remaining = budget_ms - (time.monotonic() - arrival) * 1e3
            if remaining <= 0:
                return None  # dispatch loop answers DeadlineExceeded
            pf["deadline_ms"] = remaining
            timeout_s = min(timeout_s, remaining / 1e3 + 1.0)
        sp = (rtr.begin("prefill_handoff", parent=rtr.anchor,
                        replica=target.idx)
              if rtr is not None else None)
        try:
            reply = _rpc(self.sup.host, target.port, pf,
                         timeout_s=timeout_s)
        except Exception as e:
            reply = {"error": f"{type(e).__name__}", "reason": str(e)}
        if not reply.get("prefilled"):
            with self._lock:
                self.handoff_prefill_failures_total += 1
            trace("handoff_prefill_failed", rep=target.idx,
                  err=reply.get("error"))
            if rtr is not None:
                rtr.end(sp, error=str(reply.get("error"))[:120])
            return None  # plain dispatch: local prefill, bit-identical
        with self._lock:
            self.handoffs_total += 1
        trace("handoff_prefill", rep=target.idx,
              pages=len(reply.get("keys") or ()))
        if rtr is not None:
            rtr.end(sp, pages=len(reply.get("keys") or ()))
        return {"host": self.sup.host, "port": target.port}

    def _forward(self, rep: Replica, msg: Dict, send,
                 progress: Dict[str, int]) -> None:
        """Proxy one request to ``rep``; stream token messages through,
        suppressing the first ``progress["relayed"]`` (already
        delivered by a prior attempt — bit-identical by greedy
        determinism), advancing the count IN PLACE so progress
        survives a mid-stream `_BackendLost`. Raises `_BackendLost` if
        the backend dies before the final reply, `_ClientLost` if the
        router's own client can no longer be written to."""
        from ..distributed.fault_inject import (InjectedFault,
                                                fault_point)

        def to_client(reply: Dict) -> None:
            # client-side send failures get their own exception class
            # so the backend-loss handler below can't mistake a dead
            # CLIENT for a dead REPLICA and fail over for nothing
            try:
                send(reply)
            except Exception as e:
                raise _ClientLost(f"{type(e).__name__}: {e}")

        seen = 0
        try:
            with socket.create_connection(
                    (self.sup.host, rep.port),
                    timeout=self.backend_timeout_s) as s:
                f = s.makefile("rw", encoding="utf-8")
                f.write(json.dumps(msg) + "\n")
                f.flush()
                while True:
                    fault_point("net.recv")
                    line = f.readline()
                    if not line:
                        raise _BackendLost(
                            f"replica {rep.idx} closed mid-request")
                    try:
                        reply = json.loads(line)
                    except json.JSONDecodeError:
                        raise _BackendLost(
                            f"replica {rep.idx} sent torn JSON")
                    if "token" in reply:
                        seen += 1
                        if seen > progress["relayed"]:
                            to_client(reply)
                            progress["relayed"] = seen
                        continue
                    # final reply (result or typed error)
                    to_client(reply)
                    return
        except InjectedFault as e:
            raise _BackendLost(f"injected net.recv ({e})")
        except (OSError, ValueError) as e:
            if isinstance(e, (_BackendLost, _ClientLost)):
                raise
            raise _BackendLost(f"{type(e).__name__}: {e}")


def main(argv=None) -> None:
    import argparse
    parser = argparse.ArgumentParser(
        description="paddle_tpu serving supervisor: N replica server "
                    "processes + health-probed restarts + failover "
                    "router on one port")
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--model", default="gpt_125m")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8770,
                        help="router (client-facing) port")
    parser.add_argument("--probe-interval-s", type=float, default=0.5)
    parser.add_argument("--backoff-base-s", type=float, default=0.5)
    parser.add_argument("--log-dir", default=None)
    parser.add_argument(
        "--roles", default=None, metavar="R0,R1,...",
        help="disaggregated serving (r20): comma list assigning each "
             "replica a role (mixed/prefill/decode; shorter lists pad "
             "with mixed) — e.g. --replicas 3 --roles prefill,decode,"
             "decode runs one prefill-class replica shipping finished "
             "KV chains to two decode-class replicas through the "
             "router's prefill-first dispatch. Omit for an all-mixed "
             "fleet (byte-for-byte the pre-r20 behavior)")
    parser.add_argument(
        "--no-disaggregate", action="store_true",
        help="disable the router's prefill-first dispatch even when "
             "prefill-class replicas exist (keyed requests then route "
             "by plain cache affinity; prefill replicas only serve "
             "explicit prefill_only/fetch_pages traffic)")
    parser.add_argument(
        "--checkpoint", default=None, metavar="DIR",
        help="boot every replica from the newest valid checkpoint in "
             "DIR (r24); later, `{\"op\": \"roll\", \"checkpoint\": "
             "...}` on the router hot-swaps the fleet onto a new "
             "checkpoint replica-by-replica with canary auto-rollback")
    parser.add_argument(
        "--mesh", default=None, metavar="model=N",
        help="tensor-parallel mesh per replica, threaded to every "
             "replica's server as its --mesh (each replica shards over "
             "its OWN process-local devices — replicas stay "
             "independent fault domains)")
    parser.add_argument(
        "--prefill-chunk", type=int, default=None, metavar="TOKENS",
        help="chunked prefill per replica, threaded to every "
             "replica's server as its --prefill-chunk (page-aligned "
             "tokens prefilled per decode step; default: whole-prompt "
             "prefill)")
    parser.add_argument(
        "--no-fused-step", action="store_true",
        help="disable the fused decode hot path on every replica "
             "(threaded to each replica's server as its "
             "--no-fused-step; fused is the default, greedy outputs "
             "are bit-identical either way)")
    parser.add_argument(
        "--multi-step", type=int, default=1, metavar="N",
        help="device-resident multi-step decode per replica (r19), "
             "threaded to every replica's server as its --multi-step: "
             "N decode steps per device program launch (1 = the "
             "per-token default; greedy outputs are bit-identical "
             "for any N)")
    parser.add_argument(
        "--spill-mb", type=int, default=None, metavar="MB",
        help="hierarchical prefix cache per replica (r15): host-RAM "
             "spill tier of this many MB, threaded to every replica's "
             "server as its --spill-mb; pairs with the router's "
             "cache-affinity steering (keyed requests land on the "
             "replica whose tiers hold their prefix)")
    parser.add_argument(
        "--spill-dir", default=None, metavar="DIR",
        help="disk spill tier per replica: each replica i gets "
             "DIR/replica<i> as its --spill-dir (per-replica subdirs "
             "keep blob namespaces disjoint)")
    parser.add_argument(
        "--spill-disk-mb", type=int, default=1024, metavar="MB",
        help="byte budget of each replica's disk tier (with "
             "--spill-dir; default 1024)")
    parser.add_argument(
        "--trace-sample", type=float, default=0.0, metavar="R",
        help="end-to-end request tracing (r16): the ROUTER samples "
             "this fraction of requests; a sampled request's forward "
             "carries a trace context so the replica traces it too — "
             "one trace id from router pick/forward/failover spans "
             "down to the engine's decode steps. Also threaded to "
             "every replica's server as its --trace-sample so "
             "replica-local sampling works when the router doesn't "
             "sample")
    parser.add_argument(
        "--slo-ttft-ms", type=float, default=None, metavar="MS",
        help="fleet telemetry (r17): TTFT target for the live "
             "SLO-attainment monitor, threaded to every replica's "
             "server; per-class rolling-window attainment surfaces as "
             "serving_slo_attainment gauges and merges into the "
             "router's fleet_stats op (the 3(a) autoscaler signal)")
    parser.add_argument(
        "--slo-tpot-ms", type=float, default=None, metavar="MS",
        help="TPOT target for the live SLO monitor (see --slo-ttft-ms)")
    parser.add_argument(
        "--flight-dir", default=None, metavar="DIR",
        help="crash flight recorder (r17): each replica i writes "
             "black-box bundles (step timeline, sampled traces, "
             "metrics export, inflight dump, engine recipe) to "
             "DIR/replica<i> on engine resurrection / terminal "
             "EngineFailed / stalled-request eviction; inspect with "
             "tools/flight_inspect.py")
    parser.add_argument(
        "--flight-budget-mb", type=int, default=64, metavar="MB",
        help="byte budget of each replica's flight-bundle retention "
             "ring (oldest bundles pruned; default 64)")
    parser.add_argument(
        "--no-collect-metrics", action="store_true",
        help="disable the fleet metrics collector (the probe cycle's "
             "per-replica export scrape); fleet_stats then reports "
             "supervision state only (no merged counters/SLO/"
             "pressure) and fleet_metrics answers typed "
             "FleetMetricsUnavailable")
    parser.add_argument(
        "--deprioritize-outliers", action="store_true",
        help="steer unkeyed traffic away from replicas the fleet "
             "outlier detector flags (slow step-ms/TPOT or erroring "
             "vs the fleet median); default off — detection always "
             "runs, only the routing preference is gated")
    parser.add_argument(
        "--autoscale", action="store_true",
        help="autoscaling actuator (r21): a supervisor control loop "
             "consumes the PressureMonitor verdict and spawns a "
             "replica on scale_up / drains-then-kills one on "
             "scale_down inside the --min/--max-replicas envelope, "
             "and on disaggregated fleets drives the prefill:decode "
             "ratio by RE-ROLING replicas (drain + restart with a "
             "new --role). Every action is journaled to an atomic "
             "crc-checked fleet-state file BEFORE the process "
             "action; a restarted supervisor adopts the journal's "
             "fleet and resumes or rolls back half-finished actions")
    parser.add_argument("--min-replicas", type=int, default=1,
                        help="autoscale floor (default 1)")
    parser.add_argument("--max-replicas", type=int, default=4,
                        help="autoscale ceiling (default 4)")
    parser.add_argument(
        "--cooldown-s", type=float, default=30.0,
        help="seconds between scale actions per direction (scale-up "
             "and scale-down/rerole each keep their own clock; "
             "default 30)")
    parser.add_argument(
        "--autoscale-interval-s", type=float, default=1.0,
        help="actuator tick interval (default 1.0)")
    parser.add_argument(
        "--journal", default=None, metavar="PATH",
        help="fleet-state journal path (default: "
             "<log-dir>/fleet-journal.json). Crash recovery adopts "
             "the fleet recorded here — point a restarted supervisor "
             "at the SAME journal (and --log-dir) to inherit the "
             "previous generation's replicas instead of orphaning "
             "them")
    parser.add_argument(
        "--no-fleet-cache", action="store_true",
        help="disable the r23 fleet-cache lane: when the picked "
             "replica does not advertise a keyed request's chain, the "
             "router normally hints it to fetch the pages from "
             "whichever live peer DOES advertise it (any replica's "
             "spill tiers act as a fleet-wide KV cache); this flag "
             "restores pick-then-local-prefill routing")
    parser.add_argument(
        "--forecast-placement", action="store_true",
        help="byte-planning placement (r23): steer new requests away "
             "from replicas whose exhaustion forecast (fleet_capacity "
             "tte_s) is under the pressure floor; default off — the "
             "forecast is always scraped, only the routing preference "
             "is gated")
    parser.add_argument(
        "server_args", nargs="*",
        help="extra args passed to every replica's "
             "`python -m paddle_tpu.serving.server` (e.g. "
             "--page-size 64 --stall-timeout-s 30)")
    args = parser.parse_args(argv)

    def _sigterm(signum, frame):
        # `kill`, docker stop, systemd stop all speak SIGTERM; the
        # default handler would take the supervisor down WITHOUT the
        # cleanup below and orphan the whole replica tree. Route it
        # through the same path as ^C.
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _sigterm)

    server_args = list(args.server_args)
    if args.mesh is not None:
        # validate HERE so a typo fails the supervisor loudly instead
        # of crash-looping N replicas through spawn/backoff until
        # wait_ready's ready_timeout_s finally raises
        from ..distributed.topology import parse_mesh_spec
        try:
            mp_degree = parse_mesh_spec(args.mesh)
        except ValueError as e:
            raise SystemExit(f"--mesh: {e}")
        # device-count probe in a SUBPROCESS with the replicas' exact
        # (inherited) environment: importing jax here would initialize
        # a backend in the supervisor parent — on exclusive-access
        # accelerators that could starve the very replicas it spawns.
        # An inconclusive probe proceeds; the replica surfaces the real
        # error and wait_ready points at its log.
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(len(jax.devices()))"],
                capture_output=True, text=True, timeout=120)
            ndev = int(probe.stdout.strip().splitlines()[-1]) \
                if probe.returncode == 0 else None
        except Exception:
            ndev = None
        if ndev is not None and mp_degree > ndev:
            raise SystemExit(
                f"--mesh model={mp_degree} exceeds the {ndev} "
                f"device(s) a replica will see; lower the degree or "
                f"raise the device count (e.g. XLA_FLAGS="
                f"--xla_force_host_platform_device_count=N for CPU)")
        server_args += ["--mesh", args.mesh]
    if args.prefill_chunk is not None:
        server_args += ["--prefill-chunk", str(args.prefill_chunk)]
    if args.no_fused_step:
        server_args += ["--no-fused-step"]
    if args.multi_step != 1:
        server_args += ["--multi-step", str(args.multi_step)]
    if args.spill_mb is not None:
        server_args += ["--spill-mb", str(args.spill_mb)]
    if args.spill_dir is not None:
        server_args += ["--spill-dir",
                        os.path.join(args.spill_dir, "replica{replica}"),
                        "--spill-disk-mb", str(args.spill_disk_mb)]
    if args.trace_sample:
        server_args += ["--trace-sample", str(args.trace_sample)]
    if args.slo_ttft_ms is not None:
        server_args += ["--slo-ttft-ms", str(args.slo_ttft_ms)]
    if args.slo_tpot_ms is not None:
        server_args += ["--slo-tpot-ms", str(args.slo_tpot_ms)]
    if args.flight_dir is not None:
        server_args += ["--flight-dir",
                        os.path.join(args.flight_dir,
                                     "replica{replica}"),
                        "--flight-budget-mb",
                        str(args.flight_budget_mb)]
    roles = None
    if args.roles:
        roles = [r.strip() for r in args.roles.split(",") if r.strip()]
        bad = [r for r in roles
               if r not in ("mixed", "prefill", "decode")]
        if bad:
            raise SystemExit(f"--roles: unknown role(s) {bad}; choose "
                             f"from mixed/prefill/decode")
    sup = Supervisor(model=args.model, replicas=args.replicas,
                     host=args.host, server_args=server_args,
                     probe_interval_s=args.probe_interval_s,
                     backoff_base_s=args.backoff_base_s,
                     log_dir=args.log_dir,
                     collect_metrics=not args.no_collect_metrics,
                     roles=roles, checkpoint=args.checkpoint)
    print(f"[paddle_tpu.supervisor] spawning {args.replicas} replicas "
          f"of {args.model} (logs: {sup.log_dir}) ...", flush=True)
    asc = None
    if args.autoscale:
        from .autoscaler import AutoscaleConfig, Autoscaler
        flight = None
        if args.flight_dir is not None:
            from .fleet_metrics import FlightRecorder
            # min_interval_s=0: scale actions are rare and each one
            # matters for the postmortem — never rate-limit them
            flight = FlightRecorder(
                os.path.join(args.flight_dir, "supervisor"),
                budget_bytes=args.flight_budget_mb << 20,
                min_interval_s=0.0)
        asc = Autoscaler(
            sup,
            AutoscaleConfig(min_replicas=args.min_replicas,
                            max_replicas=args.max_replicas,
                            cooldown_up_s=args.cooldown_s,
                            cooldown_down_s=args.cooldown_s,
                            interval_s=args.autoscale_interval_s),
            journal_path=args.journal, flight=flight)
        # recovery BEFORE start(): adopt the previous generation's
        # live replicas (journal + env-marker scan) so start() only
        # spawns what recovery says is dead — never a double-spawn
        rec = asc.recover()
        print(f"[paddle_tpu.supervisor] autoscale journal "
              f"{asc.journal.path}: adopted "
              f"{[a['idx'] for a in rec['adopted']]}, respawning "
              f"{[a['idx'] for a in rec['respawned']]}, reaped "
              f"{len(rec['reaped'])}, resolved "
              f"{len(rec['resolved'])}, resuming "
              f"{len(rec['resumed'])} action(s)", flush=True)
    router = None
    try:
        sup.start(wait_ready=True)
        router = FailoverRouter(
            sup, host=args.host, port=args.port,
            trace_sample=args.trace_sample,
            deprioritize_outliers=args.deprioritize_outliers,
            disaggregate=not args.no_disaggregate,
            fleet_cache=not args.no_fleet_cache,
            forecast_placement=args.forecast_placement)
        port = router.start()
        if asc is not None:
            asc.start()
        print(f"[paddle_tpu.supervisor] router on {args.host}:{port}; "
              f"replicas "
              f"{[(r.idx, r.port) for r in sup.replicas]}", flush=True)
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("[paddle_tpu.supervisor] stopping ...", flush=True)
    finally:
        # every exit path — ^C, SIGTERM, a bound --port (OSError from
        # router.start), a replica that never came ready — must tear
        # down whatever was spawned; N orphaned replica processes are
        # never an acceptable residue
        if asc is not None:
            asc.stop()
        if router is not None:
            router.stop()
        sup.stop()


if __name__ == "__main__":
    main()
