"""Version-compat shims over jax API churn.

The codebase targets current jax spellings; the runtime container may
carry an older release. Everything here degrades to a passthrough when
the running jax already has the new API:

- ``shard_map``: promoted to ``jax.shard_map`` (new) from
  ``jax.experimental.shard_map`` (old), and the replication-check kwarg
  renamed ``check_rep`` -> ``check_vma`` along the way; this wrapper
  accepts either and translates to whatever the running jax expects.
- ``axis_size``: ``jax.lax.axis_size`` (new); on older jax
  ``lax.psum(1, axis)`` constant-folds to the same static int at trace
  time.
"""

from __future__ import annotations

import inspect

import jax

try:
    from jax import shard_map as _shard_map_impl
except ImportError:  # pre-promotion jax
    from jax.experimental.shard_map import shard_map as _shard_map_impl

_SM_PARAMS = frozenset(
    inspect.signature(_shard_map_impl).parameters)


def shard_map(f, *args, **kwargs):
    if "check_vma" in kwargs and "check_vma" not in _SM_PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" in kwargs and "check_rep" not in _SM_PARAMS:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    if "axis_names" in kwargs and "axis_names" not in _SM_PARAMS:
        # new: axis_names = the MANUAL subset; old: auto = its complement
        manual = set(kwargs.pop("axis_names"))
        mesh_axes = getattr(kwargs.get("mesh"), "axis_names", ())
        kwargs["auto"] = frozenset(a for a in mesh_axes
                                   if a not in manual)
    return _shard_map_impl(f, *args, **kwargs)


if hasattr(jax.lax, "axis_size"):
    axis_size = jax.lax.axis_size
else:
    def axis_size(axis_name):
        return jax.lax.psum(1, axis_name)
