"""Structured sparsity (2:4 ASP).

Reference parity: python/paddle/fluid/contrib/sparsity/ (asp.py —
prune_model with 2:4 masks, decorate() masking optimizer updates,
check_sparsity). TPU note: the MXU has no sparse-math unit, so 2:4 here
is a *model-compression* capability (mask-enforced training, smaller
checkpoints), matching the reference's functional behavior.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from .nn.layer import Layer
from .tensor import Parameter

_MASKS: Dict[int, jnp.ndarray] = {}


def compute_mask_2d_best(weight: np.ndarray, n: int = 2, m: int = 4
                         ) -> np.ndarray:
    """n:m sparsity along the last axis: keep the n largest of every m."""
    w = np.asarray(weight)
    flat = np.abs(w.reshape(-1, w.shape[-1]))
    mask = np.zeros_like(flat, dtype=bool)
    cols = flat.shape[1]
    usable = cols - cols % m
    for r in range(flat.shape[0]):
        row = flat[r, :usable].reshape(-1, m)
        keep = np.argsort(-row, axis=1)[:, :n]
        for g in range(row.shape[0]):
            mask[r, g * m + keep[g]] = True
        mask[r, usable:] = True
    return mask.reshape(w.shape)


def check_sparsity(weight, n: int = 2, m: int = 4) -> bool:
    w = np.asarray(weight)
    flat = (w.reshape(-1, w.shape[-1]) != 0)
    cols = flat.shape[1]
    usable = cols - cols % m
    groups = flat[:, :usable].reshape(-1, m)
    return bool((groups.sum(axis=1) <= n).all())


def _prunable(name: str, p: Parameter) -> bool:
    return (p is not None and p.ndim == 2 and p.shape[-1] % 4 == 0 and
            "weight" in name)


def prune_model(model: Layer, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d") -> Dict[str, np.ndarray]:
    """Apply n:m masks to prunable weights; masks are remembered so
    decorated optimizers re-apply them after each step."""
    masks = {}
    for name, p in model.named_parameters():
        if _prunable(name, p):
            mask = compute_mask_2d_best(np.asarray(p.value), n, m)
            p.value = p.value * jnp.asarray(mask, dtype=p.dtype)
            _MASKS[id(p)] = jnp.asarray(mask, dtype=p.dtype)
            masks[name] = mask
    return masks


def decorate(optimizer):
    """Wrap optimizer.step to re-mask pruned weights after the update
    (reference: sparsity.decorate -> OptimizerWithSparsityGuarantee)."""
    orig_step = optimizer.step

    def step():
        orig_step()
        for p in optimizer._parameter_list or []:
            mask = _MASKS.get(id(p))
            if mask is not None:
                p.value = p.value * mask

    optimizer.step = step
    return optimizer


def reset_masks() -> None:
    _MASKS.clear()
