"""Dy2Static: AST-level rewrite of Python control flow to traceable form.

Converts a dygraph-style Python function into an equivalent function
whose ``if``/``while``/``for range()``/``break``/``continue``/``return``
statements are rewritten into calls to the runtime dispatch helpers in
``paddle_tpu.jit.convert_ops``. Concrete (Python) conditions keep exact
Python semantics; tensor-dependent conditions lower to ``lax.cond`` /
``lax.while_loop`` so the whole function stays jittable with
data-dependent control flow — the capability the reference implements
with its AST transformer suite (python/paddle/fluid/dygraph/
dygraph_to_static/: ifelse_transformer.py, loop_transformer.py,
break_continue_transformer.py, return_transformer.py,
logical_transformer.py, assert_transformer.py) over cond/while ops.

Pipeline (per function, nested defs untouched):
  1. for-range  → while            (iterator var threaded explicitly)
  2. break/continue → flag vars + tail guards; loop-else lifted
  3. return     → flag var + value var + tail guards
  4. and/or/not → short-circuit-preserving convert_logical_* calls
  5. assert     → convert_assert
  6. if/while   → branch/body functions + convert_ifelse/convert_while

Known limits (same family as the reference's): object mutation inside a
tensor-dependent branch runs on both paths; branches must produce
type-compatible values; nested function defs keep Python control flow.
"""

from __future__ import annotations

import ast
import functools
import inspect
import linecache
import textwrap
from typing import List, Optional, Sequence

_D2S = "__pt_d2s__"
_FN_PREFIX = "__pt_fn_"


# ---------------------------------------------------------------- ast utils

def _load(name: str) -> ast.Name:
    return ast.Name(id=name, ctx=ast.Load())


def _store(name: str) -> ast.Name:
    return ast.Name(id=name, ctx=ast.Store())


def _d2s(attr: str) -> ast.Attribute:
    return ast.Attribute(value=_load(_D2S), attr=attr, ctx=ast.Load())


def _call(func: ast.expr, args: Sequence[ast.expr]) -> ast.Call:
    return ast.Call(func=func, args=list(args), keywords=[])


def _assign(name: str, value: ast.expr) -> ast.Assign:
    return ast.Assign(targets=[_store(name)], value=value)


def _const(v) -> ast.Constant:
    return ast.Constant(value=v)


def _tuple_load(names: Sequence[str]) -> ast.Tuple:
    return ast.Tuple(elts=[_load(n) for n in names], ctx=ast.Load())


def _tuple_store(names: Sequence[str]) -> ast.Tuple:
    return ast.Tuple(elts=[_store(n) for n in names], ctx=ast.Store())


def _not(e: ast.expr) -> ast.UnaryOp:
    return ast.UnaryOp(op=ast.Not(), operand=e)


def _and(a: ast.expr, b: ast.expr) -> ast.BoolOp:
    return ast.BoolOp(op=ast.And(), values=[a, b])


def _arglist(names: Sequence[str]) -> ast.arguments:
    return ast.arguments(
        posonlyargs=[], args=[ast.arg(arg=n) for n in names],
        vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
        defaults=[])


def _undef_preamble(name: str) -> ast.Try:
    """try: name / except NameError: name = UNDEF  — makes a possibly
    unbound local readable as the UNDEF sentinel before branch capture."""
    return ast.Try(
        body=[ast.Expr(value=_load(name))],
        handlers=[ast.ExceptHandler(
            type=_load("NameError"), name=None,
            body=[_assign(name, _d2s("UNDEF"))])],
        orelse=[], finalbody=[])


_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)
_COMP_NODES = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)


class _AssignedNames(ast.NodeVisitor):
    """Ordered set of names bound in a statement list, within the current
    function scope (no descent into nested defs/lambdas/comprehensions)."""

    def __init__(self):
        self.names: List[str] = []
        self._seen = set()

    def _add(self, n: str):
        if n not in self._seen:
            self._seen.add(n)
            self.names.append(n)

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Store):
            self._add(node.id)

    def visit_FunctionDef(self, node):
        if not node.name.startswith(_FN_PREFIX):
            self._add(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self._add(node.name)

    def visit_Lambda(self, node):
        pass

    def visit_ListComp(self, node):
        pass

    visit_SetComp = visit_DictComp = visit_GeneratorExp = visit_ListComp


def _assigned_names(stmts: Sequence[ast.stmt]) -> List[str]:
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


def _contains_exit(node, kinds, stop_at_loops: bool) -> bool:
    """Does `node` contain a break/continue/return belonging to the
    current construct? Never descends into nested function scopes;
    optionally stops at nested loops (for break/continue ownership)."""
    found = [False]

    class V(ast.NodeVisitor):
        def visit_Break(self, n):
            if "break" in kinds:
                found[0] = True

        def visit_Continue(self, n):
            if "continue" in kinds:
                found[0] = True

        def visit_Return(self, n):
            if "return" in kinds:
                found[0] = True

        def visit_FunctionDef(self, n):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef
        visit_ClassDef = visit_FunctionDef

        def visit_For(self, n):
            if not stop_at_loops:
                self.generic_visit(n)

        visit_While = visit_For

    V().visit(node)
    return found[0]


# ------------------------------------------------------------------ passes

class _Namer:
    def __init__(self):
        self.n = 0

    def fresh(self, base: str) -> str:
        self.n += 1
        return f"{base}_{self.n}"


class _ForRangeToWhile(ast.NodeTransformer):
    """for i in range(...) → explicit-counter while (increment happens
    before the body so continue/break guards cannot skip it)."""

    def __init__(self, namer: _Namer):
        self.namer = namer

    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    def visit_For(self, node):
        self.generic_visit(node)
        it = node.iter
        if not (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range" and not it.keywords
                and 1 <= len(it.args) <= 3
                and not any(isinstance(a, ast.Starred) for a in it.args)):
            return node
        if len(it.args) == 1:
            start, stop, step = _const(0), it.args[0], _const(1)
        elif len(it.args) == 2:
            start, stop, step = it.args[0], it.args[1], _const(1)
        else:
            start, stop, step = it.args
        iv = self.namer.fresh("__pt_it")
        sv = self.namer.fresh("__pt_stop")
        pv = self.namer.fresh("__pt_step")
        body = [
            ast.Assign(targets=[node.target], value=_load(iv)),
            _assign(iv, ast.BinOp(left=_load(iv), op=ast.Add(),
                                  right=_load(pv))),
        ] + node.body
        w = ast.While(
            test=_call(_d2s("range_continue"),
                       [_load(iv), _load(sv), _load(pv)]),
            body=body, orelse=node.orelse)
        return [_assign(iv, start), _assign(sv, stop), _assign(pv, step), w]


class _FlagRewriter:
    """Shared machinery: replace exit statements with flag assignments and
    guard the statements that follow them with `if not flag:`."""

    def __init__(self, kinds, stop_at_loops, make_replacement,
                 guard_test_fn, loop_test_hook=None):
        self.kinds = kinds
        self.stop_at_loops = stop_at_loops
        self.make_replacement = make_replacement
        self.guard_test_fn = guard_test_fn
        self.loop_test_hook = loop_test_hook

    def _is_exit(self, st):
        return (isinstance(st, ast.Break) and "break" in self.kinds) or \
            (isinstance(st, ast.Continue) and "continue" in self.kinds) or \
            (isinstance(st, ast.Return) and "return" in self.kinds)

    def rewrite(self, stmts: List[ast.stmt]) -> List[ast.stmt]:
        out: List[ast.stmt] = []
        for i, st in enumerate(stmts):
            if self._is_exit(st):
                out.extend(self.make_replacement(st))
                sets = True
            else:
                sets = _contains_exit(st, self.kinds, self.stop_at_loops)
                if sets:
                    self._descend(st)
                out.append(st)
            if sets and i < len(stmts) - 1:
                rest = self.rewrite(list(stmts[i + 1:]))
                out.append(ast.If(test=self.guard_test_fn(),
                                  body=rest, orelse=[]))
                return out
        return out

    def _descend(self, st):
        if isinstance(st, _SCOPE_NODES):
            return
        if isinstance(st, (ast.For, ast.While)):
            if self.stop_at_loops:
                return
            st.body = self.rewrite(st.body)
            if st.orelse:
                st.orelse = self.rewrite(st.orelse)
            if self.loop_test_hook is not None:
                self.loop_test_hook(st)
            return
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(st, field, None)
            if sub:
                setattr(st, field, self.rewrite(sub))
        for handler in getattr(st, "handlers", []) or []:
            handler.body = self.rewrite(handler.body)


class _BreakContinue(ast.NodeTransformer):
    """break/continue → flags + guards; loop else-clause lifted out."""

    def __init__(self, namer: _Namer):
        self.namer = namer

    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    def _transform_loop(self, node):
        self.generic_visit(node)
        has_break = any(_contains_exit(s, {"break"}, True)
                        for s in node.body)
        has_cont = any(_contains_exit(s, {"continue"}, True)
                       for s in node.body)
        if not (has_break or has_cont):
            if node.orelse:
                orelse, node.orelse = node.orelse, []
                return [node] + orelse
            return node
        bflag = self.namer.fresh("__pt_brk") if has_break else None
        cflag = self.namer.fresh("__pt_cont") if has_cont else None

        def guard_test():
            flags = [f for f in (bflag, cflag) if f]
            e = _load(flags[0])
            for f in flags[1:]:
                e = ast.BoolOp(op=ast.Or(), values=[e, _load(f)])
            return _not(e)

        def replacement(st):
            if isinstance(st, ast.Break):
                return [_assign(bflag, _const(True))]
            return [_assign(cflag, _const(True))]

        kinds = set()
        if has_break:
            kinds.add("break")
        if has_cont:
            kinds.add("continue")
        rw = _FlagRewriter(kinds, True, replacement, guard_test)
        body = rw.rewrite(node.body)
        if cflag:
            body = [_assign(cflag, _const(False))] + body
        pre: List[ast.stmt] = []
        post: List[ast.stmt] = []
        if bflag:
            pre.append(_assign(bflag, _const(False)))
        if isinstance(node, ast.While):
            if bflag:
                node.test = _and(node.test, _not(_load(bflag)))
            node.body = body
        else:  # Python for kept: guard whole body on the break flag
            node.body = [ast.If(test=_not(_load(bflag)), body=body,
                                orelse=[])] if bflag else body
        if node.orelse:
            orelse, node.orelse = node.orelse, []
            if bflag:
                post.append(ast.If(test=_not(_load(bflag)), body=orelse,
                                   orelse=[]))
            else:
                post.extend(orelse)
        return pre + [node] + post

    visit_While = _transform_loop
    visit_For = _transform_loop


class _ReturnTransform:
    """Nested returns → (__pt_ret_flag, __pt_ret_val) + guards."""

    RFLAG = "__pt_ret_flag"
    RVAL = "__pt_ret_val"

    def apply(self, func: ast.FunctionDef) -> None:
        nested = False
        for st in func.body:
            if not isinstance(st, ast.Return) and \
                    _contains_exit(st, {"return"}, False):
                nested = True
                break
        if not nested:
            return

        def replacement(st: ast.Return):
            val = st.value if st.value is not None else _const(None)
            return [_assign(self.RVAL, val),
                    _assign(self.RFLAG, _const(True))]

        def guard_test():
            return _not(_load(self.RFLAG))

        def loop_hook(loop):
            if isinstance(loop, ast.While):
                loop.test = _and(loop.test, _not(_load(self.RFLAG)))
            else:
                loop.body = [ast.If(test=_not(_load(self.RFLAG)),
                                    body=loop.body, orelse=[])]

        rw = _FlagRewriter({"return"}, False, replacement, guard_test,
                           loop_test_hook=loop_hook)
        body = rw.rewrite(func.body)
        func.body = [
            _assign(self.RFLAG, _const(False)),
            _assign(self.RVAL, _d2s("UNDEF")),
        ] + body + [
            ast.Return(value=_call(_d2s("finalize_ret"),
                                   [_load(self.RVAL)]))
        ]


class _Logical(ast.NodeTransformer):
    """and/or → lazy convert_logical_* calls; not → convert_logical_not."""

    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        name = "convert_logical_and" if isinstance(node.op, ast.And) \
            else "convert_logical_or"
        expr = node.values[-1]
        for v in reversed(node.values[:-1]):
            expr = _call(_d2s(name), [
                ast.Lambda(args=_arglist([]), body=v),
                ast.Lambda(args=_arglist([]), body=expr)])
        return expr

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return _call(_d2s("convert_logical_not"), [node.operand])
        return node

    def visit_Assert(self, node):
        self.generic_visit(node)
        args = [node.test]
        if node.msg is not None:
            args.append(ast.Lambda(args=_arglist([]), body=node.msg))
        return ast.Expr(value=_call(_d2s("convert_assert"), args))


class _ControlFlow(ast.NodeTransformer):
    """if → convert_ifelse, while → convert_while (post-order)."""

    def __init__(self, namer: _Namer):
        self.namer = namer

    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    def visit_If(self, node):
        self.generic_visit(node)
        names = _assigned_names(node.body + node.orelse)
        fn_t = self.namer.fresh(_FN_PREFIX + "true")
        fn_f = self.namer.fresh(_FN_PREFIX + "false")
        ret = ast.Return(value=_tuple_load(names))
        def_t = ast.FunctionDef(
            name=fn_t, args=_arglist(names),
            body=node.body + [ret], decorator_list=[])
        def_f = ast.FunctionDef(
            name=fn_f, args=_arglist(names),
            body=(node.orelse or []) + [ast.Return(
                value=_tuple_load(names))], decorator_list=[])
        pre = [_undef_preamble(n) for n in names]
        call = _call(_d2s("convert_ifelse"), [
            node.test,
            ast.Lambda(args=_arglist([]), body=_call(_load(fn_t),
                                                     [_load(n)
                                                      for n in names])),
            ast.Lambda(args=_arglist([]), body=_call(_load(fn_f),
                                                     [_load(n)
                                                      for n in names])),
        ])
        if names:
            out = ast.Assign(targets=[_tuple_store(names)], value=call)
        else:
            out = ast.Expr(value=call)
        return [def_t, def_f] + pre + [out]

    def visit_While(self, node):
        self.generic_visit(node)
        names = _assigned_names(node.body)
        fn_c = self.namer.fresh(_FN_PREFIX + "cond")
        fn_b = self.namer.fresh(_FN_PREFIX + "body")
        def_c = ast.FunctionDef(
            name=fn_c, args=_arglist(names),
            body=[ast.Return(value=node.test)], decorator_list=[])
        def_b = ast.FunctionDef(
            name=fn_b, args=_arglist(names),
            body=node.body + [ast.Return(value=_tuple_load(names))],
            decorator_list=[])
        pre = [_undef_preamble(n) for n in names]
        call = _call(_d2s("convert_while"),
                     [_load(fn_c), _load(fn_b), _tuple_load(names)])
        if names:
            out = ast.Assign(targets=[_tuple_store(names)], value=call)
        else:
            out = ast.Expr(value=call)
        return [def_c, def_b] + pre + [out]


# ------------------------------------------------------------------- entry

def _transform_function(func: ast.FunctionDef) -> None:
    namer = _Namer()
    func.body = _apply(_ForRangeToWhile(namer), func.body)
    func.body = _apply(_BreakContinue(namer), func.body)
    _ReturnTransform().apply(func)
    func.body = _apply(_Logical(), func.body)
    func.body = _apply(_ControlFlow(namer), func.body)


def _apply(transformer: ast.NodeTransformer,
           stmts: List[ast.stmt]) -> List[ast.stmt]:
    out: List[ast.stmt] = []
    for st in stmts:
        r = transformer.visit(st)
        if r is None:
            continue
        if isinstance(r, list):
            out.extend(r)
        else:
            out.append(r)
    return out


_counter = [0]


def convert_to_static(fn, *, raise_on_error: bool = False):
    """Rewrite `fn`'s control flow into traceable form. Returns `fn`
    unchanged when the source is unavailable or conversion fails (the
    plain tracer still handles tensor-independent control flow)."""
    if getattr(fn, "__pt_converted__", False) or not callable(fn):
        return fn
    if getattr(fn, "__pt_not_to_static__", False):
        # user opt-out (paddle.jit.not_to_static)
        return fn
    try:
        return _convert(fn)
    except Exception:
        if raise_on_error:
            raise
        return fn


def _convert(fn):
    from . import convert_ops

    src = textwrap.dedent(inspect.getsource(fn))
    tree = ast.parse(src)
    func = tree.body[0]
    if not isinstance(func, ast.FunctionDef):
        return fn
    func.decorator_list = []
    _transform_function(func)

    freevars = fn.__code__.co_freevars
    if freevars:
        factory = ast.FunctionDef(
            name="__pt_factory__", args=_arglist(list(freevars)),
            body=[func, ast.Return(value=_load(func.name))],
            decorator_list=[])
        mod = ast.Module(body=[factory], type_ignores=[])
    else:
        mod = ast.Module(body=[func], type_ignores=[])
    ast.fix_missing_locations(mod)
    code_str = ast.unparse(mod)

    _counter[0] += 1
    filename = f"<dy2static:{getattr(fn, '__qualname__', fn.__name__)}" \
               f"#{_counter[0]}>"
    linecache.cache[filename] = (
        len(code_str), None, code_str.splitlines(True), filename)
    import types
    # Compile in a scratch namespace to obtain code objects, then build
    # the final function over the ORIGINAL module globals and ORIGINAL
    # closure cells, so later rebinding of captured/global names stays
    # visible exactly as it would be to the unconverted function.
    scratch = {_D2S: convert_ops}
    exec(compile(code_str, filename, "exec"), scratch)
    real_globals = fn.__globals__
    real_globals[_D2S] = convert_ops
    if freevars:
        placeholder = scratch["__pt_factory__"](*[None] * len(freevars))
        code = placeholder.__code__
        cell_by_name = dict(zip(fn.__code__.co_freevars, fn.__closure__))
        closure = tuple(cell_by_name[n] for n in code.co_freevars)
    else:
        code = scratch[func.name].__code__
        closure = None
    new_fn = types.FunctionType(code, real_globals, func.name,
                                fn.__defaults__, closure)
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    new_fn = functools.wraps(fn)(new_fn)
    new_fn.__pt_converted__ = True
    new_fn.__pt_source__ = code_str
    return new_fn


class ProgramTranslator:
    """Global switch for dy2static conversion
    (reference: program_translator.py:759 ProgramTranslator)."""

    _instance = None
    enabled = True

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    @classmethod
    def enable(cls, flag: bool) -> None:
        cls.enabled = bool(flag)


def enable_to_static(flag: bool) -> None:
    ProgramTranslator.enable(flag)
