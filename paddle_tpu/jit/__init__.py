"""paddle_tpu.jit — traced execution.

TPU-native replacement for the reference's two static paths:
- ``to_static`` / ``TrainStep``: capture eager-style Layer code into ONE
  jitted XLA computation (replaces ProgramDesc+Executor op-loop,
  reference: python/paddle/fluid/dygraph/dygraph_to_static/
  program_translator.py:232 StaticFunction). Autodiff happens inside the
  trace via jax.grad — the analog of append_backward's program transform.
- ``save``/``load``: serialize a traced function + params
  (reference: fluid/dygraph/jit.py:515 save / :851 load).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from ..autograd.engine import no_grad
from ..core import rng as rng_mod
from ..nn.layer import Layer, bind_state, functional_state
from ..tensor import Tensor


def _unwrap_tree(tree):
    return jax.tree_util.tree_map(
        lambda t: t.value if isinstance(t, Tensor) else t, tree,
        is_leaf=lambda t: isinstance(t, Tensor))


def _wrap_tree(tree):
    return jax.tree_util.tree_map(
        lambda v: Tensor(v) if isinstance(v, jax.Array) else v, tree)


def effects_token_guard(target_devices) -> None:
    """Barrier stale ordered-effects tokens before dispatching onto a
    DIFFERENT device set.

    jax keeps one token per ordered effect (io_callback in a
    HostEmbedding backward, ordered debug prints...), sharded over the
    devices of the last program that used it. Dispatching a program on
    another device set makes get_token_input reshard that token with a
    device_put — which on jax<0.5 dies in a native CHECK (token arrays
    cannot take the slow copy path), aborting the process. Running
    ``jax.effects_barrier()`` first is always safe: it waits for the
    outstanding effects (preserving ordering) and drops the tokens, so
    the next program mints a fresh one on its own devices."""
    try:
        from jax._src import dispatch as _jd
        tokens = _jd.runtime_tokens.current_tokens
    except (ImportError, AttributeError):
        return
    if not tokens:
        return
    target = set(target_devices)
    for tok in list(tokens.values()):
        buf = getattr(tok, "_buf", None)
        devs = getattr(getattr(buf, "sharding", None), "device_set", None)
        if devs is not None and set(devs) != target:
            jax.effects_barrier()
            return


def _devices_of(leaf) -> tuple:
    devs = getattr(getattr(leaf, "sharding", None), "device_set", None)
    if devs:
        return tuple(devs)
    return (jax.devices()[0],)


def cached_lr_device(obj, optimizer):
    """Device f32 scalar for the current lr, re-uploaded only when the
    value changes — a fresh jnp.asarray per step is a host->device
    transfer (milliseconds of round-trip on tunneled runtimes)."""
    lr = float(optimizer.get_lr())
    cache = getattr(obj, "_lr_cache", None)
    if cache is None or lr != cache[0]:
        obj._lr_cache = (lr, jnp.asarray(lr, jnp.float32))
    return obj._lr_cache[1]


class TrainStep:
    """One fused, jitted train step over an eager-style step function.

    ``train_fn(model, batch) -> loss`` is ordinary eager Layer code; it is
    traced once into an XLA computation containing forward, backward
    (jax.grad) and the optimizer update — the op-by-op interpreter loop the
    reference executes per step collapses into a single device launch.
    """

    def __init__(self, model: Layer, optimizer, train_fn: Callable,
                 donate: bool = True, seed: int = 0):
        self.model = model
        self.optimizer = optimizer
        self.train_fn = train_fn
        state = functional_state(model)
        self.params = state["params"]
        self.buffers = state["buffers"]
        self.opt_state = optimizer.init(self.params)
        self._key = jax.random.key(seed)
        self._lr_cache = None
        self._step, self._multi = self._build(donate)

    def _build(self, donate: bool):
        model, optimizer, train_fn = self.model, self.optimizer, \
            self.train_fn

        def one_step(params, buffers, opt_state, key, lr, batch):
            key, sub = jax.random.split(key)

            def loss_of(p):
                model.train()
                with bind_state(model, {"params": p, "buffers": buffers}), \
                        no_grad(), rng_mod.key_scope(sub):
                    loss = train_fn(model, _wrap_tree(batch))
                    new_buf = {n: b.value for n, b in model.named_buffers()
                               if b is not None}
                loss_raw = loss.value if isinstance(loss, Tensor) else loss
                return loss_raw, new_buf

            (loss, new_buf), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
            new_params, new_opt = optimizer.apply_gradients(
                params, grads, opt_state, lr=lr)
            return new_params, new_buf, new_opt, key, loss

        # The PRNG key evolves INSIDE the jitted step: one device dispatch
        # per step total. A separate host-side jax.random.split is a whole
        # extra launch, which on remote/tunneled TPU runtimes costs
        # milliseconds of round-trip per step.
        kwargs = {"donate_argnums": (0, 1, 2, 3)} if donate else {}
        step = jax.jit(one_step, **kwargs)

        def multi_impl(params, buffers, opt_state, key, lr, batches):
            def body(carry, batch):
                p, b, o, k = carry
                p, b, o, k, loss = one_step(p, b, o, k, lr, batch)
                return (p, b, o, k), loss

            (params, buffers, opt_state, key), losses = jax.lax.scan(
                body, (params, buffers, opt_state, key), batches)
            return params, buffers, opt_state, key, losses

        multi = jax.jit(multi_impl, **kwargs)
        return step, multi

    def _lr_device(self):
        return cached_lr_device(self, self.optimizer)

    def __call__(self, batch) -> jax.Array:
        batch_raw = _unwrap_tree(batch)
        leaf = next(iter(self.params.values()), None)
        effects_token_guard(_devices_of(leaf))
        self.params, self.buffers, self.opt_state, self._key, loss = \
            self._step(self.params, self.buffers, self.opt_state,
                       self._key, self._lr_device(), batch_raw)
        return loss

    def multi_step(self, batches) -> jax.Array:
        """Run a whole micro-epoch in ONE device launch: ``batches`` is a
        pytree whose leaves are stacked along a leading steps axis; the
        jitted program lax.scans the train step over it. TPU-native analog
        of the reference's C++ trainer loop (Executor::RunFromDataset,
        framework/trainer.h) — the hot loop never returns to Python.
        Returns the per-step losses [n_steps]."""
        batches_raw = _unwrap_tree(batches)
        self.params, self.buffers, self.opt_state, self._key, losses = \
            self._multi(self.params, self.buffers, self.opt_state,
                        self._key, self._lr_device(), batches_raw)
        return losses

    def sync_to_model(self) -> None:
        """Write the jitted state back into the eager Layer's parameters."""
        named_p = dict(self.model.named_parameters())
        for n, v in self.params.items():
            if n in named_p:
                named_p[n].value = v
        named_b = dict(self.model.named_buffers())
        for n, v in self.buffers.items():
            if n in named_b:
                named_b[n].value = v


class EvalStep:
    """Jitted inference step: out = model(*inputs) with frozen state."""

    def __init__(self, model: Layer, seed: int = 0):
        self.model = model
        state = functional_state(model)
        self.params = state["params"]
        self.buffers = state["buffers"]

        def fwd(params, buffers, key, args, kwargs):
            model.eval()
            with bind_state(model, {"params": params, "buffers": buffers}), \
                    no_grad(), rng_mod.key_scope(key):
                out = model(*_wrap_tree(args), **_wrap_tree(kwargs))
            return _unwrap_tree(out)

        self._fwd = jax.jit(fwd)
        self._key = jax.random.key(seed)

    def __call__(self, *args, **kwargs):
        self._key, sub = jax.random.split(self._key)
        return self._fwd(self.params, self.buffers, sub,
                         _unwrap_tree(args), _unwrap_tree(kwargs))


class StaticFunction:
    """to_static-decorated function: cached jit over Layer state; the
    dy2static AST pass first rewrites tensor-dependent Python control
    flow into lax control flow so it survives tracing
    (reference: program_translator.py StaticFunction)."""

    def __init__(self, fn: Callable, model: Optional[Layer] = None):
        self._orig_fn = fn
        self._converted_fn = None
        self.model = model
        self._jitted_by_mode: Dict[bool, Any] = {}

    @property
    def fn(self) -> Callable:
        """Resolve per call so enable_to_static() toggles take effect
        after decoration (reference: ProgramTranslator.enable)."""
        if not ProgramTranslator.enabled:
            return self._orig_fn
        if self._converted_fn is None:
            self._converted_fn = convert_to_static(self._orig_fn)
        return self._converted_fn

    @property
    def _jitted(self):
        return self._jitted_by_mode.get(ProgramTranslator.enabled)

    @_jitted.setter
    def _jitted(self, value):
        self._jitted_by_mode[ProgramTranslator.enabled] = value

    def _resolve_model(self, args):
        if self.model is not None:
            return self.model
        if args and isinstance(args[0], Layer):
            return args[0]
        return None

    def __call__(self, *args, **kwargs):
        model = self._resolve_model(args)
        if model is None:
            if self._jitted is None:
                raw_fn = self.fn
                self._jitted = jax.jit(lambda a, k: _unwrap_tree(
                    raw_fn(*_wrap_tree(a), **_wrap_tree(k))))
            return _wrap_tree(self._jitted(_unwrap_tree(args),
                                           _unwrap_tree(kwargs)))
        rest = args[1:] if args and args[0] is model else args
        if self._jitted is None:
            fn = self.fn

            def traced(params, buffers, a, k):
                with bind_state(model, {"params": params,
                                        "buffers": buffers}), no_grad():
                    out = fn(model, *_wrap_tree(a), **_wrap_tree(k)) \
                        if args and args[0] is model else \
                        fn(*_wrap_tree(a), **_wrap_tree(k))
                return _unwrap_tree(out)

            self._jitted = jax.jit(traced)
        state = functional_state(model)
        out = self._jitted(state["params"], state["buffers"],
                           _unwrap_tree(rest), _unwrap_tree(kwargs))
        return _wrap_tree(out)


def to_static(function=None, input_spec=None, **kwargs):
    """Decorator: trace an eager function/Layer into a cached jitted
    computation (reference: paddle.jit.to_static)."""
    def deco(fn):
        if isinstance(fn, Layer):
            layer = fn
            sf = StaticFunction(type(layer).forward, model=layer)
            layer._static_forward = sf
            layer.forward = functools.partial(_call_static, layer)
            return layer
        return functools.wraps(fn)(StaticFunction(fn))
    if function is not None:
        return deco(function)
    return deco


def _call_static(layer, *args, **kwargs):
    return layer._static_forward(layer, *args, **kwargs)


def save(layer, path: str, input_spec=None) -> None:
    """Serialize layer state + config for later load
    (reference: paddle.jit.save). The exported artifact stores the
    state_dict; the program artifact (StableHLO export) is produced by
    paddle_tpu.static.export when shapes are pinned."""
    from ..framework.io import save as fsave
    fsave({"state_dict": layer.state_dict(),
           "class": f"{type(layer).__module__}.{type(layer).__qualname__}"},
          path + ".pdparams")


def load(path: str):
    from ..framework.io import load as fload
    return fload(path + ".pdparams")


from .dy2static import (ProgramTranslator, convert_to_static,  # noqa: E402
                        enable_to_static)


def not_to_static(fn=None):
    """Mark a function to be skipped by to_static conversion
    (reference: paddle.jit.not_to_static)."""
    def deco(f):
        f.__pt_not_to_static__ = True
        return f
    return deco(fn) if fn is not None else deco


_code_level = 0
_verbosity = 0


def set_code_level(level: int = 100, also_to_stdout: bool = False) -> None:
    """reference: paddle.jit.set_code_level — controls dumping of the
    transformed code (here: the dy2static-rewritten AST source)."""
    global _code_level
    _code_level = int(level)


def set_verbosity(level: int = 0, also_to_stdout: bool = False) -> None:
    """reference: paddle.jit.set_verbosity."""
    global _verbosity
    _verbosity = int(level)


class TracedLayer:
    """reference: paddle.jit.TracedLayer (fluid/dygraph/jit.py) — a
    layer captured by running it once on example inputs. Here the trace
    is a static Program; ``trace`` returns (eager_outputs, traced)."""

    def __init__(self, program, layer):
        self._program = program
        self._layer = layer

    @staticmethod
    def trace(layer, inputs):
        from ..static import InputSpec, build_program
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        outs = layer(*ins)
        specs = [InputSpec.from_tensor(i) for i in ins]
        program = build_program(layer, specs)
        return outs, TracedLayer(program, layer)

    def __call__(self, inputs):
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        return self._program.run(*ins)

    def save_inference_model(self, path, feed=None, fetch=None):
        self._program.save(path)


class TranslatedLayer:
    """reference: paddle.jit.TranslatedLayer (fluid/dygraph/io.py:1082) —
    a Layer reconstructed from a saved program artifact; forward runs the
    loaded StableHLO computation."""

    def __init__(self, loaded_program):
        self._loaded = loaded_program
        self.training = False

    @classmethod
    def from_path(cls, path_prefix: str):
        from ..static import load_inference_model
        return cls(load_inference_model(path_prefix))

    def __call__(self, *inputs):
        return self.forward(*inputs)

    def forward(self, *inputs):
        return self._loaded.run(*inputs)

    def eval(self):
        self.training = False
        return self

    def train(self):
        raise RuntimeError(
            "TranslatedLayer wraps a frozen inference artifact; retraining "
            "requires the original Layer (reference TranslatedLayer "
            "supports train mode only for programs saved with dropout "
            "etc. intact)")
