"""Runtime dispatch helpers for dy2static-converted code.

The AST transformer (``paddle_tpu.jit.dy2static``) rewrites Python
control flow into calls to these helpers. Each helper dispatches at run
time: concrete (Python) conditions keep ordinary Python semantics;
traced (jax tracer) conditions lower to ``lax.cond`` /
``lax.while_loop`` so the converted function stays fully jittable.

Reference analog: python/paddle/fluid/dygraph/dygraph_to_static/
convert_operators.py (convert_ifelse, convert_while_loop,
convert_logical_and/or/not) — rebuilt on lax control-flow primitives
instead of Paddle's cond/while ops.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


class _Undefined:
    """Sentinel for names that may be unbound on one control path
    (reference: dygraph_to_static/utils.py UndefinedVar)."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<pt undefined>"

    def __bool__(self):
        raise NameError(
            "variable is undefined on this control path (dy2static)")


UNDEF = _Undefined()


def _raw(x):
    """Unwrap paddle_tpu.Tensor to its jax value."""
    from ..tensor import Tensor
    return x.value if isinstance(x, Tensor) else x


def _is_traced(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _to_pred(cond):
    cond = _raw(cond)
    if isinstance(cond, (jax.Array,)) or _is_traced(cond):
        if getattr(cond, "ndim", 0) != 0:
            # Match Python/JAX semantics: a multi-element condition is a
            # user bug, not something to silently reduce.
            raise ValueError(
                "dy2static: the truth value of a condition with "
                f"shape {jnp.shape(cond)} is ambiguous; reduce it with "
                ".all()/.any() first")
        return cond.astype(jnp.bool_) if cond.dtype != jnp.bool_ else cond
    return cond


def _unify_one(a, b):
    """Unify one output pair across branches. UNDEF on one side is
    filled with zeros_like of the other — that value is only observable
    if user code reads a variable that was never assigned on the taken
    path, which plain Python would reject with NameError."""
    ra, rb = _raw(a), _raw(b)
    a_undef = ra is UNDEF
    b_undef = rb is UNDEF
    if a_undef and b_undef:
        return UNDEF, UNDEF, True
    if a_undef:
        if isinstance(rb, (jax.Array,)) or _is_traced(rb):
            return jnp.zeros(jnp.shape(rb), rb.dtype), rb, False
        return rb, rb, False
    if b_undef:
        if isinstance(ra, (jax.Array,)) or _is_traced(ra):
            return ra, jnp.zeros(jnp.shape(ra), ra.dtype), False
        return ra, ra, False
    return ra, rb, False


def convert_ifelse(pred, true_fn: Callable[[], Tuple],
                   false_fn: Callable[[], Tuple]):
    """``if pred: ... else: ...`` with branch bodies extracted into
    functions returning the tuple of assigned names."""
    pred = _to_pred(pred)
    if not (_is_traced(pred)):
        # Concrete: run only the selected branch (ordinary Python).
        return true_fn() if bool(pred) else false_fn()

    # Traced: probe both branches once to unify output structure; the
    # probe traces are unreachable from any output, so they never enter
    # the final jaxpr. Real branch execution happens inside lax.cond.
    outs_t = true_fn()
    outs_f = false_fn()
    if len(outs_t) != len(outs_f):
        raise TypeError(
            "dy2static: if/else branches produced different numbers of "
            f"outputs ({len(outs_t)} vs {len(outs_f)})")

    def _is_static_slot(a, b):
        a, b = _raw(a), _raw(b)
        if a is UNDEF and b is UNDEF:
            return True
        if a is None and b is None:
            return True
        return False

    static_mask = [_is_static_slot(a, b) for a, b in zip(outs_t, outs_f)]
    static_vals = [_raw(a) for a, s in zip(outs_t, static_mask) if s]

    def _wrap(fn, other):
        def branch():
            outs = fn()
            res = []
            for v, o, s in zip(outs, other, static_mask):
                if s:
                    continue
                rv, ro = _raw(v), _raw(o)
                if rv is UNDEF:
                    rv = jnp.zeros(jnp.shape(ro), jnp.result_type(ro))
                res.append(jnp.asarray(rv))
            return tuple(res)
        return branch

    picked = lax.cond(pred, _wrap(true_fn, outs_f),
                      _wrap(false_fn, outs_t))
    it_dyn = iter(picked)
    it_static = iter(static_vals)
    return tuple(next(it_static) if s else next(it_dyn)
                 for s in static_mask)


def convert_while(cond_fn: Callable, body_fn: Callable,
                  init_vars: Tuple):
    """``while cond: body`` with loop-carried names passed explicitly.
    A concrete condition runs as an ordinary Python loop; if the
    condition becomes traced (possibly mid-loop, e.g. a break flag
    turning into a tracer), the remaining iterations lower to
    lax.while_loop from the current state."""
    vars_ = tuple(init_vars)
    while True:
        c = _to_pred(cond_fn(*vars_))
        if _is_traced(c):
            return _traced_while(cond_fn, body_fn, vars_)
        if not bool(c):
            return vars_
        vars_ = tuple(body_fn(*vars_))


def _traced_while(cond_fn, body_fn, init_vars):
    # Run the body once eagerly to learn output structure and fill
    # UNDEF slots in the carry; the probe trace is dead code.
    probe = tuple(body_fn(*init_vars))
    init = []
    for a, b in zip(init_vars, probe):
        ua, _, is_static = _unify_one(a, b)
        init.append(UNDEF if is_static else ua)
    static_mask = [v is UNDEF for v in init]
    statics = [v for v in init if v is UNDEF]

    def pack(full):
        return tuple(v for v, s in zip(full, static_mask) if not s)

    def unpack(dyn):
        it = iter(dyn)
        return tuple(UNDEF if s else next(it) for s in static_mask)

    def cond_w(carry):
        return _to_pred(cond_fn(*unpack(carry)))

    def body_w(carry):
        out = body_fn(*unpack(carry))
        return pack(tuple(_raw(v) for v in out))

    final = lax.while_loop(cond_w, body_w,
                           pack(tuple(_raw(v) for v in init)))
    return unpack(final)


def convert_logical_and(lhs_fn: Callable, rhs_fn: Callable):
    """``a and b`` preserving short-circuit for concrete lhs."""
    lhs = lhs_fn()
    raw = _raw(lhs)
    if _is_traced(raw) or isinstance(raw, jax.Array):
        return jnp.logical_and(_to_pred(lhs), _to_pred(rhs_fn()))
    return lhs and rhs_fn()


def convert_logical_or(lhs_fn: Callable, rhs_fn: Callable):
    lhs = lhs_fn()
    raw = _raw(lhs)
    if _is_traced(raw) or isinstance(raw, jax.Array):
        return jnp.logical_or(_to_pred(lhs), _to_pred(rhs_fn()))
    return lhs or rhs_fn()


def convert_logical_not(x):
    raw = _raw(x)
    if _is_traced(raw) or isinstance(raw, jax.Array):
        return jnp.logical_not(_to_pred(raw))
    return not x


def convert_assert(test, msg_fn=None):
    """Traced assertions are skipped (XLA has no host assert); concrete
    ones keep Python semantics. ``msg_fn`` is lazy — the message
    expression only evaluates on failure, as in plain ``assert``."""
    raw = _raw(test)
    if _is_traced(raw) or isinstance(raw, jax.Array):
        return
    if not test:
        raise AssertionError(msg_fn() if msg_fn is not None else "")


def finalize_ret(v):
    """A function that falls off the end without returning yields None."""
    return None if _raw(v) is UNDEF else v


def range_continue(i, stop, step):
    """Continuation predicate of a lowered ``for i in range(...)``."""
    ri, rstop, rstep = _raw(i), _raw(stop), _raw(step)
    if any(_is_traced(v) or isinstance(v, jax.Array)
           for v in (ri, rstop, rstep)):
        return jnp.where(jnp.asarray(rstep) > 0,
                         jnp.asarray(ri) < jnp.asarray(rstop),
                         jnp.asarray(ri) > jnp.asarray(rstop))
    return ri < rstop if rstep > 0 else ri > rstop
