"""Device management module (reference: python/paddle/device.py __all__:
get_cudnn_version, set_device, get_device, XPUPlace,
is_compiled_with_xpu/cuda/rocm/npu).

TPU-native: the accelerator is a TPU reached through PJRT; the
is_compiled_with_* probes answer for the CUDA/ROCm/XPU/NPU stacks this
build intentionally does not carry.
"""

from __future__ import annotations

from .core import get_device, set_device
from .core.place import XPUPlace

__all__ = ["get_cudnn_version", "set_device", "get_device", "XPUPlace",
           "is_compiled_with_xpu", "is_compiled_with_cuda",
           "is_compiled_with_rocm", "is_compiled_with_npu",
           "is_compiled_with_tpu"]


def get_cudnn_version():
    """reference: paddle.device.get_cudnn_version — None when no cuDNN
    (this build targets TPU)."""
    return None


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    """Beyond-reference probe: True — the TPU backend is the point."""
    return True
