"""Eager op dispatch: Tensor unwrap → pure kernel → tape record → rewrap.

TPU-native equivalent of the reference's dygraph fast-op path + tracer
(reference: paddle/fluid/pybind/op_function_generator.cc:518 generated
core.ops.* entries; paddle/fluid/imperative/tracer.cc:133 TraceOp which runs
the shared kernel then CreateGradOpNode at tracer.cc:207). Here the shared
kernel is a pure jax function from paddle_tpu.ops; grad recording uses the
kernel's own jax.vjp pullback, so every op in the library is differentiable
for free — no hand-written grad ops.

The same wrapped entry points work inside jit traces: a Tensor may hold a
tracer, and with autograd disabled (functional capture) dispatch reduces to
unwrap→call→rewrap.
"""

from __future__ import annotations

import contextlib
import inspect
import threading
import time
from functools import partial
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np

from .autograd.engine import GradNode, is_grad_enabled
from .core.flags import get_flag
from .core.monitor import stat
from .ops.registry import all_ops, get_op
from .tensor import Tensor

_is_tensor = lambda x: isinstance(x, Tensor)  # noqa: E731


def _flatten(args, kwargs):
    return jax.tree_util.tree_flatten((args, kwargs))


def _is_diff_dtype(v) -> bool:
    try:
        return jnp.issubdtype(v.dtype, jnp.inexact)
    except Exception:
        return False


from jax._src import core as _jax_core


_no_constraints_cm = None

# -- trace-time op/launch counter (fused decode hot path, r13) --------------
#
# Every dispatch-op call inside an active `count_op_calls()` scope bumps
# the counter. A jit executes its COMPILED program without re-entering
# dispatch, so wrapping a jit call counts exactly the ops traced into
# the program on a (re)trace and zero on a cache hit — which makes the
# count a per-program "kernel ops" figure: the launch-counter currency
# the fused-decode A/B and the `serving_step_programs` gauge report.
# THREAD-LOCAL: the serving engine traces on its own engine thread
# while other threads keep dispatching eagerly.

_OP_COUNTER = threading.local()


class OpCallCounter:
    """Mutable counter handle yielded by :func:`count_op_calls`."""

    __slots__ = ("count",)

    def __init__(self):
        self.count = 0


@contextlib.contextmanager
def count_op_calls():
    """Count dispatch-op calls on this thread for the duration (nested
    scopes shadow, outer scope resumes unchanged)."""
    prev = getattr(_OP_COUNTER, "counter", None)
    c = OpCallCounter()
    _OP_COUNTER.counter = c
    try:
        yield c
    finally:
        _OP_COUNTER.counter = prev


def _no_sharding_constraints():
    global _no_constraints_cm
    if _no_constraints_cm is None:
        from .distributed.mp_layers import no_sharding_constraints
        _no_constraints_cm = no_sharding_constraints
    return _no_constraints_cm


def call_fn(fn: Callable, name: str, differentiable: bool, args, kwargs):
    _c = getattr(_OP_COUNTER, "counter", None)
    if _c is not None:
        _c.count += 1
    leaves, treedef = _flatten(args, kwargs)
    tensor_idx = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
    if not tensor_idx:
        out = fn(*args, **kwargs)
        # Eager creation ops (no tensor inputs) still produce Tensors;
        # inside a jit trace raw tracers pass through untouched.
        if _jax_core.trace_state_clean():
            return _wrap_outputs(out, None, name)
        return out

    raw_leaves = [l.value if isinstance(l, Tensor) else l for l in leaves]
    record = (differentiable and is_grad_enabled() and
              any(not leaves[i].stop_gradient and
                  _is_diff_dtype(leaves[i]) for i in tensor_idx))

    from .amp.auto_cast import amp_state, amp_target_dtype
    if amp_state() is not None:
        target = amp_target_dtype(name)
        if target is not None:
            fn = _amp_wrap(fn, target)

    bench = get_flag("benchmark")
    t0 = time.perf_counter() if bench else 0.0

    if not record:
        a, kw = jax.tree_util.tree_unflatten(treedef, raw_leaves)
        out_raw = fn(*a, **kw)
        out = _wrap_outputs(out_raw, None, name)
    else:
        diff_idx = [i for i in tensor_idx
                    if not leaves[i].stop_gradient and
                    _is_diff_dtype(leaves[i])]

        def closed(*dvals):
            rl = list(raw_leaves)
            for i, v in zip(diff_idx, dvals):
                rl[i] = v
            a, kw = jax.tree_util.tree_unflatten(treedef, rl)
            return fn(*a, **kw)

        primals = [raw_leaves[i] for i in diff_idx]
        # Eager-tape recording traces the kernel with jax.vjp, which would
        # make mp-layer sharding constraints fire (they skip plain eager
        # via trace_state_clean but can't tell this trace from a pjit
        # capture). Eager semantics = single-device concrete arrays, so
        # constraints stay off, matching un-taped eager dispatch.
        with _no_sharding_constraints()():
            out_raw, vjp_fn = jax.vjp(closed, *primals)
        out_leaves, out_tree = jax.tree_util.tree_flatten(out_raw)
        avals = [jax.ShapeDtypeStruct(jnp.shape(o), jnp.result_type(o))
                 for o in out_leaves]
        node = GradNode(name, vjp_fn, [leaves[i] for i in diff_idx], avals,
                        out_tree)
        # create_graph support: the engine re-dispatches vjp(closed).
        # Marginal retention is just the closure object — raw_leaves is
        # already pinned by vjp_fn's residuals (constants in its jaxpr),
        # and backward() clears fwd_fn alongside vjp_fn.
        node.fwd_fn = closed
        out = _wrap_outputs(out_raw, node, name)

    if get_flag("check_nan_inf"):
        _check_nan_inf(out, name)
    if bench:
        jax.block_until_ready(jax.tree_util.tree_leaves(
            out, is_leaf=_is_tensor))
        stat(f"op_us/{name}").add(int((time.perf_counter() - t0) * 1e6))
    stat("eager_op_calls").add(1)
    return out


def _amp_wrap(fn: Callable, target) -> Callable:
    """Cast floating array inputs to ``target`` inside the kernel, so the
    cast participates in the vjp (grads flow back in the original dtype)."""

    def casted(*args, **kwargs):
        def c(x):
            if hasattr(x, "dtype") and jnp.issubdtype(
                    jnp.result_type(x), jnp.floating) and x.dtype != target:
                return jnp.asarray(x).astype(target)
            return x
        args = jax.tree_util.tree_map(c, args)
        kwargs = jax.tree_util.tree_map(c, kwargs)
        return fn(*args, **kwargs)

    return casted


def _wrap_outputs(out_raw, node, name):
    out_leaves, out_tree = jax.tree_util.tree_flatten(out_raw)
    wrapped = []
    for i, o in enumerate(out_leaves):
        if isinstance(o, (jax.Array, np.ndarray)) or hasattr(o, "dtype"):
            t = Tensor(o, stop_gradient=(node is None or
                                         not _is_diff_dtype(o)))
            if node is not None:
                t.grad_node = node
                t._out_index = i
                node.out_tensors.append(t)
            wrapped.append(t)
        else:
            wrapped.append(o)
    return jax.tree_util.tree_unflatten(out_tree, wrapped)


def _check_nan_inf(out, name):
    for t in jax.tree_util.tree_leaves(out, is_leaf=_is_tensor):
        if isinstance(t, Tensor) and _is_diff_dtype(t):
            if bool(jnp.any(~jnp.isfinite(t.value))):
                from .core.enforce import EnforceNotMet
                raise EnforceNotMet(
                    f"Operator {name} output contains NaN/Inf "
                    f"(FLAGS_check_nan_inf is on)")


def apply(name: str, *args, **kwargs):
    opdef = get_op(name)
    return call_fn(opdef.fn, name, opdef.differentiable, args, kwargs)


def wrap_op(name: str) -> Callable:
    opdef = get_op(name)

    def wrapped(*args, **kwargs):
        return call_fn(opdef.fn, name, opdef.differentiable, args, kwargs)

    wrapped.__name__ = name
    wrapped.__qualname__ = name
    wrapped.__doc__ = opdef.fn.__doc__
    wrapped.__wrapped__ = opdef.fn
    try:
        wrapped.__signature__ = inspect.signature(opdef.fn)
    except (TypeError, ValueError):
        pass
    return wrapped


# -- indexing ----------------------------------------------------------------

def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx.value
    if isinstance(idx, tuple):
        return tuple(_unwrap_index(i) for i in idx)
    if isinstance(idx, list):
        return [_unwrap_index(i) for i in idx]
    if isinstance(idx, slice):
        return slice(_unwrap_index(idx.start), _unwrap_index(idx.stop),
                     _unwrap_index(idx.step))
    return idx


def getitem(t: Tensor, idx):
    idx_raw = _unwrap_index(idx)
    return call_fn(lambda x: x[idx_raw], "getitem", True, (t,), {})


def setitem(t: Tensor, idx, value):
    idx_raw = _unwrap_index(idx)
    if isinstance(value, Tensor):
        new = call_fn(lambda x, v: x.at[idx_raw].set(v.astype(x.dtype)),
                      "setitem", True, (t, value), {})
    else:
        v = jnp.asarray(value)
        new = call_fn(lambda x: x.at[idx_raw].set(v.astype(x.dtype)),
                      "setitem", True, (t,), {})
    t._inplace_assign(new)
    return t


# -- public wrapped namespace ------------------------------------------------

wrapped_ops: Dict[str, Callable] = {}


def _build_namespace():
    for name in all_ops():
        wrapped_ops[name] = wrap_op(name)


_build_namespace()


# -- Tensor monkey-patching (reference: varbase_patch_methods.py) -----------

_BINARY_DUNDERS = {
    "__add__": "add", "__radd__": ("add", True),
    "__sub__": "subtract", "__rsub__": ("subtract", True),
    "__mul__": "multiply", "__rmul__": ("multiply", True),
    "__truediv__": "divide", "__rtruediv__": ("divide", True),
    "__floordiv__": "floor_divide", "__rfloordiv__": ("floor_divide", True),
    "__mod__": "mod", "__rmod__": ("mod", True),
    "__pow__": "pow", "__rpow__": ("pow", True),
    "__matmul__": "matmul", "__rmatmul__": ("matmul", True),
    "__eq__": "equal", "__ne__": "not_equal",
    "__lt__": "less_than", "__le__": "less_equal",
    "__gt__": "greater_than", "__ge__": "greater_equal",
    "__and__": "logical_and", "__or__": "logical_or",
    "__xor__": "logical_xor",
}

_UNARY_DUNDERS = {"__neg__": "neg", "__abs__": "abs",
                  "__invert__": "logical_not"}


def _make_binary(opname, reflected=False):
    fn = wrapped_ops[opname]
    if reflected:
        def dunder(self, other):
            return fn(other, self)
    else:
        def dunder(self, other):
            return fn(self, other)
    return dunder


def monkey_patch_tensor():
    for dunder, spec in _BINARY_DUNDERS.items():
        if isinstance(spec, tuple):
            setattr(Tensor, dunder, _make_binary(spec[0], True))
        else:
            setattr(Tensor, dunder, _make_binary(spec))
    for dunder, opname in _UNARY_DUNDERS.items():
        fn = wrapped_ops[opname]
        setattr(Tensor, dunder, lambda self, _f=fn: _f(self))

    # Attach every op whose leading parameter is a tensor as a method.
    for name, w in wrapped_ops.items():
        if hasattr(Tensor, name):
            continue
        try:
            params = list(inspect.signature(w).parameters)
        except (TypeError, ValueError):
            continue
        if params and params[0] in ("x", "input", "logits", "logit"):
            setattr(Tensor, name, _method_from(w))


def _method_from(w):
    def method(self, *args, **kwargs):
        return w(self, *args, **kwargs)
    method.__name__ = w.__name__
    method.__doc__ = w.__doc__
    return method


monkey_patch_tensor()
