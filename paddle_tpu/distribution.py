"""Probability distributions (reference: python/paddle/distribution.py:
Distribution:41, Uniform:168, Normal:390, Categorical:640).

Same API surface (sample/entropy/log_prob/probs/kl_divergence), jax-native:
sampling uses the framework RNG stream (core/rng.py) so results are
reproducible under paddle_tpu.seed, and every method is safe under jit
when given a key explicitly.
"""

from __future__ import annotations

import math
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import Tensor


def _val(x):
    if isinstance(x, Tensor):
        return x.value
    return jnp.asarray(x, jnp.float32) if not isinstance(x, jax.Array) \
        else x


def _key(seed):
    if seed:
        return jax.random.PRNGKey(seed)
    from .core.rng import next_key
    return next_key()


class Distribution:
    """Abstract base (reference distribution.py:41)."""

    def sample(self, shape, seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        raise NotImplementedError

    # -- argument plumbing (reference distribution.py:70-136) ---------------

    @staticmethod
    def _validate_args(*args):
        """Mixing Tensors with python numbers/lists is rejected, exactly
        like the reference (:70): returns True iff args are Tensors."""
        is_variable = any(isinstance(a, (Tensor, jax.Array)) for a in args)
        is_number = any(not isinstance(a, (Tensor, jax.Array))
                        for a in args)
        if is_variable and is_number:
            raise ValueError(
                "if one argument is Tensor, all arguments should be "
                "Tensor")
        return is_variable

    @staticmethod
    def _to_tensor(*args):
        """Convert float/list/ndarray args to mutually-broadcast f32/f64
        arrays (reference :92 _to_tensor): floats become shape-[1]
        tensors, dtypes outside {f32, f64} warn and convert to f32."""
        arrays = []
        for arg in args:
            if isinstance(arg, float):
                arg = [arg]
            if isinstance(arg, int):
                arg = [float(arg)]
            if not isinstance(arg, (list, tuple, np.ndarray, Tensor,
                                    jax.Array)):
                raise TypeError(
                    "Type of input args must be float, list, "
                    "numpy.ndarray or Tensor, but received type "
                    f"{type(arg)}")
            a = np.asarray(arg.value if isinstance(arg, Tensor) else arg)
            if a.dtype not in (np.float32, np.float64):
                warnings.warn(
                    "data type of argument only support float32 and "
                    "float64, your argument will be convert to float32.")
                a = a.astype(np.float32)
            arrays.append(a)
        common = np.result_type(*arrays)
        shape = np.broadcast_shapes(*(a.shape for a in arrays))
        return tuple(jnp.asarray(np.broadcast_to(a.astype(common), shape))
                     for a in arrays)

    @staticmethod
    def _check_values_dtype_in_probs(param, value):
        """Cast ``value`` to the parameter dtype with a warning when they
        disagree (reference :136)."""
        v = value.value if isinstance(value, Tensor) else \
            jnp.asarray(value)  # keep the caller's dtype for the check
        if not jnp.issubdtype(v.dtype, jnp.floating):
            raise TypeError(
                f"value dtype must be floating, got {v.dtype}")
        p = _val(param)
        if v.dtype != p.dtype:
            warnings.warn(
                "dtype of input 'value' needs to be the same as "
                "parameters of distribution class. dtype of 'value' "
                "will be converted.")
            v = v.astype(p.dtype)
        return v


class Uniform(Distribution):
    """U(low, high) (reference distribution.py:168)."""

    def __init__(self, low, high, name=None):
        if not self._validate_args(low, high):
            low, high = self._to_tensor(low, high)
        self.low = _val(low)
        self.high = _val(high)
        self.name = name

    def sample(self, shape, seed=0):
        shape = tuple(shape) + jnp.broadcast_shapes(
            jnp.shape(self.low), jnp.shape(self.high))
        u = jax.random.uniform(_key(seed), shape, self.low.dtype)
        return Tensor(self.low + u * (self.high - self.low))

    def log_prob(self, value):
        v = self._check_values_dtype_in_probs(self.low, value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def probs(self, value):
        return Tensor(jnp.exp(self.log_prob(value).value))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Normal(Distribution):
    """N(loc, scale) (reference distribution.py:390)."""

    def __init__(self, loc, scale, name=None):
        if not self._validate_args(loc, scale):
            loc, scale = self._to_tensor(loc, scale)
        self.loc = _val(loc)
        self.scale = _val(scale)
        self.name = name

    def sample(self, shape, seed=0):
        shape = tuple(shape) + jnp.broadcast_shapes(
            jnp.shape(self.loc), jnp.shape(self.scale))
        z = jax.random.normal(_key(seed), shape, self.loc.dtype)
        return Tensor(self.loc + z * self.scale)

    def log_prob(self, value):
        v = self._check_values_dtype_in_probs(self.loc, value)
        var = self.scale * self.scale
        return Tensor(-((v - self.loc) ** 2) / (2.0 * var)
                      - jnp.log(self.scale) - 0.5 * math.log(2.0 * math.pi))

    def probs(self, value):
        return Tensor(jnp.exp(self.log_prob(value).value))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2.0 * math.pi)
                      + jnp.log(self.scale)
                      + jnp.zeros_like(self.loc))

    def kl_divergence(self, other):
        """KL(self || other) for two Normals (reference :595)."""
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return Tensor(0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio)))


class Categorical(Distribution):
    """Categorical over unnormalized logits (reference
    distribution.py:640)."""

    def __init__(self, logits, name=None):
        self.logits = _val(logits)
        self.name = name

    def _log_norm(self):
        return self.logits - jax.nn.logsumexp(self.logits, axis=-1,
                                              keepdims=True)

    def sample(self, shape, seed=0):
        draws = jax.random.categorical(
            _key(seed), self._log_norm(), axis=-1,
            shape=tuple(shape) + self.logits.shape[:-1])
        return Tensor(draws)

    def probs(self, value=None):
        p = jnp.exp(self._log_norm())
        if value is None:
            return Tensor(p)
        idx = _val(value).astype(jnp.int32)
        return Tensor(jnp.take_along_axis(
            jnp.broadcast_to(p, idx.shape[:-1] + p.shape[-1:])
            if p.ndim == 1 else p, idx, axis=-1))

    def log_prob(self, value):
        return Tensor(jnp.log(jnp.maximum(self.probs(value).value, 1e-38)))

    def entropy(self):
        logp = self._log_norm()
        return Tensor(-(jnp.exp(logp) * logp).sum(-1))

    def kl_divergence(self, other):
        """KL(self || other) for two Categoricals (reference :774)."""
        logp = self._log_norm()
        logq = other._log_norm()
        return Tensor((jnp.exp(logp) * (logp - logq)).sum(-1))
