"""Probability distributions (reference: python/paddle/distribution.py:
Distribution:41, Uniform:168, Normal:390, Categorical:640).

Same API surface (sample/entropy/log_prob/probs/kl_divergence), jax-native:
sampling uses the framework RNG stream (core/rng.py) so results are
reproducible under paddle_tpu.seed, and every method is safe under jit
when given a key explicitly.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .tensor import Tensor


def _val(x):
    if isinstance(x, Tensor):
        return x.value
    return jnp.asarray(x, jnp.float32) if not isinstance(x, jax.Array) \
        else x


def _key(seed):
    if seed:
        return jax.random.PRNGKey(seed)
    from .core.rng import next_key
    return next_key()


class Distribution:
    """Abstract base (reference distribution.py:41)."""

    def sample(self, shape, seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        raise NotImplementedError


class Uniform(Distribution):
    """U(low, high) (reference distribution.py:168)."""

    def __init__(self, low, high, name=None):
        self.low = _val(low)
        self.high = _val(high)
        self.name = name

    def sample(self, shape, seed=0):
        shape = tuple(shape) + jnp.broadcast_shapes(
            jnp.shape(self.low), jnp.shape(self.high))
        u = jax.random.uniform(_key(seed), shape, jnp.float32)
        return Tensor(self.low + u * (self.high - self.low))

    def log_prob(self, value):
        v = _val(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def probs(self, value):
        return Tensor(jnp.exp(self.log_prob(value).value))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Normal(Distribution):
    """N(loc, scale) (reference distribution.py:390)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        self.name = name

    def sample(self, shape, seed=0):
        shape = tuple(shape) + jnp.broadcast_shapes(
            jnp.shape(self.loc), jnp.shape(self.scale))
        z = jax.random.normal(_key(seed), shape, jnp.float32)
        return Tensor(self.loc + z * self.scale)

    def log_prob(self, value):
        v = _val(value)
        var = self.scale * self.scale
        return Tensor(-((v - self.loc) ** 2) / (2.0 * var)
                      - jnp.log(self.scale) - 0.5 * math.log(2.0 * math.pi))

    def probs(self, value):
        return Tensor(jnp.exp(self.log_prob(value).value))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2.0 * math.pi)
                      + jnp.log(self.scale)
                      + jnp.zeros_like(self.loc))

    def kl_divergence(self, other):
        """KL(self || other) for two Normals (reference :595)."""
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return Tensor(0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio)))


class Categorical(Distribution):
    """Categorical over unnormalized logits (reference
    distribution.py:640)."""

    def __init__(self, logits, name=None):
        self.logits = _val(logits)
        self.name = name

    def _log_norm(self):
        return self.logits - jax.nn.logsumexp(self.logits, axis=-1,
                                              keepdims=True)

    def sample(self, shape, seed=0):
        draws = jax.random.categorical(
            _key(seed), self._log_norm(), axis=-1,
            shape=tuple(shape) + self.logits.shape[:-1])
        return Tensor(draws)

    def probs(self, value=None):
        p = jnp.exp(self._log_norm())
        if value is None:
            return Tensor(p)
        idx = _val(value).astype(jnp.int32)
        return Tensor(jnp.take_along_axis(
            jnp.broadcast_to(p, idx.shape[:-1] + p.shape[-1:])
            if p.ndim == 1 else p, idx, axis=-1))

    def log_prob(self, value):
        return Tensor(jnp.log(jnp.maximum(self.probs(value).value, 1e-38)))

    def entropy(self):
        logp = self._log_norm()
        return Tensor(-(jnp.exp(logp) * logp).sum(-1))

    def kl_divergence(self, other):
        """KL(self || other) for two Categoricals (reference :774)."""
        logp = self._log_norm()
        logq = other._log_norm()
        return Tensor((jnp.exp(logp) * (logp - logq)).sum(-1))
