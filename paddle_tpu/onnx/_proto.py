"""Minimal ONNX protobuf writer/reader.

Hand-encoded protobuf wire format for the subset of onnx.proto needed to
serialize inference graphs (ModelProto / GraphProto / NodeProto /
TensorProto / ValueInfoProto / AttributeProto), following the public
ONNX schema field numbers. The development image has no ``onnx``
package; files written here are standard ONNX and load in onnx /
onnxruntime / netron outside it. ``parse`` is a generic tag-length-value
reader used by the tests to verify round-trip structure.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

import numpy as np

# ONNX TensorProto.DataType
DT_FLOAT = 1
DT_INT32 = 6
DT_INT64 = 7
DT_BOOL = 9
DT_DOUBLE = 11

NP_TO_ONNX = {
    np.dtype(np.float32): DT_FLOAT,
    np.dtype(np.int32): DT_INT32,
    np.dtype(np.int64): DT_INT64,
    np.dtype(np.bool_): DT_BOOL,
    np.dtype(np.float64): DT_DOUBLE,
}

# AttributeProto.AttributeType
AT_FLOAT, AT_INT, AT_STRING, AT_TENSOR = 1, 2, 3, 4
AT_FLOATS, AT_INTS, AT_STRINGS = 6, 7, 8


def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def f_varint(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(int(value))


def f_bytes(field: int, data: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(data)) + data


def f_string(field: int, s: str) -> bytes:
    return f_bytes(field, s.encode())


def f_float(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", float(v))


def tensor_proto(name: str, arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    out = b""
    for d in arr.shape:
        out += f_varint(1, d)                      # dims
    out += f_varint(2, NP_TO_ONNX[arr.dtype])      # data_type
    out += f_string(8, name)                       # name
    out += f_bytes(9, arr.tobytes())               # raw_data
    return out


def value_info(name: str, dtype: np.dtype, shape) -> bytes:
    shape_msg = b""
    for d in shape:
        if d is None or (isinstance(d, int) and d < 0):
            dim = f_string(2, "N")                 # dim_param
        else:
            dim = f_varint(1, int(d))              # dim_value
        shape_msg += f_bytes(1, dim)               # TensorShapeProto.dim
    tt = f_varint(1, NP_TO_ONNX[np.dtype(dtype)])  # elem_type
    tt += f_bytes(2, shape_msg)                    # shape
    tp = f_bytes(1, tt)                            # TypeProto.tensor_type
    return f_string(1, name) + f_bytes(2, tp)      # ValueInfoProto


def attribute(name: str, value) -> bytes:
    out = f_string(1, name)
    if isinstance(value, float):
        out += f_float(2, value) + f_varint(20, AT_FLOAT)
    elif isinstance(value, bool) or isinstance(value, (int, np.integer)):
        out += f_varint(3, int(value)) + f_varint(20, AT_INT)
    elif isinstance(value, str):
        out += f_bytes(4, value.encode()) + f_varint(20, AT_STRING)
    elif isinstance(value, np.ndarray):
        out += f_bytes(5, tensor_proto(name + "_t", value))
        out += f_varint(20, AT_TENSOR)
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            for v in value:
                out += f_float(7, v)
            out += f_varint(20, AT_FLOATS)
        else:
            for v in value:
                out += f_varint(8, int(v))
            out += f_varint(20, AT_INTS)
    else:
        raise TypeError(f"unsupported attribute {name}={value!r}")
    return out


def node(op_type: str, inputs: List[str], outputs: List[str],
         name: str = "", attrs: Dict[str, Any] = None) -> bytes:
    out = b""
    for i in inputs:
        out += f_string(1, i)
    for o in outputs:
        out += f_string(2, o)
    if name:
        out += f_string(3, name)
    out += f_string(4, op_type)
    for k, v in (attrs or {}).items():
        out += f_bytes(5, attribute(k, v))
    return out


def graph(nodes: List[bytes], name: str, inputs: List[bytes],
          outputs: List[bytes], initializers: List[bytes]) -> bytes:
    out = b""
    for n in nodes:
        out += f_bytes(1, n)
    out += f_string(2, name)
    for t in initializers:
        out += f_bytes(5, t)
    for i in inputs:
        out += f_bytes(11, i)
    for o in outputs:
        out += f_bytes(12, o)
    return out


def model(graph_msg: bytes, opset_version: int = 13,
          producer: str = "paddle_tpu") -> bytes:
    opset = f_string(1, "") + f_varint(2, opset_version)
    out = f_varint(1, 8)          # ir_version 8
    out += f_string(2, producer)  # producer_name
    out += f_bytes(7, graph_msg)  # graph
    out += f_bytes(8, opset)      # opset_import
    return out


# -- generic reader (tests / debugging) -----------------------------------

def parse(data: bytes) -> Dict[int, List[Tuple[int, Any]]]:
    """Decode one protobuf message into {field: [(wire_type, value)]}.
    Length-delimited values stay as bytes (parse them recursively)."""
    out: Dict[int, List[Tuple[int, Any]]] = {}
    i = 0

    def rd_varint():
        nonlocal i
        n = shift = 0
        while True:
            b = data[i]
            i += 1
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                return n
            shift += 7

    while i < len(data):
        key = rd_varint()
        field, wire = key >> 3, key & 7
        if wire == 0:
            val: Any = rd_varint()
        elif wire == 2:
            ln = rd_varint()
            val = data[i:i + ln]
            i += ln
        elif wire == 5:
            val = struct.unpack("<f", data[i:i + 4])[0]
            i += 4
        elif wire == 1:
            val = struct.unpack("<d", data[i:i + 8])[0]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        out.setdefault(field, []).append((wire, val))
    return out
