"""jaxpr -> ONNX graph conversion.

Reference: python/paddle/onnx/export.py hands a traced program to
paddle2onnx; here the traced artifact IS a jaxpr, and the supported
primitive set (the matmul/conv/elementwise/activation family that
Linear/Conv/MLP/softmax-style inference graphs lower to) maps 1:1 onto
ONNX ops. Unsupported primitives raise with the primitive name so the
scope is explicit.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import numpy as np

from . import _proto as P


class _Namer:
    def __init__(self):
        self.names: Dict[Any, str] = {}
        self.n = 0

    def of(self, var) -> str:
        if var not in self.names:
            self.n += 1
            self.names[var] = f"v{self.n}"
        return self.names[var]


def _np(v) -> np.ndarray:
    arr = np.asarray(v)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return arr


def _einsum_equation(lhs_ndim: int, rhs_ndim: int,
                     dimension_numbers) -> str:
    """dot_general dimension_numbers -> einsum equation. Output order
    follows dot_general's contract: batch dims, then lhs free dims,
    then rhs free dims."""
    (lc, rc), (lb, rb) = dimension_numbers
    letters = iter("abcdefghijklmnopqrstuvwxyz")
    lhs = [None] * lhs_ndim
    rhs = [None] * rhs_ndim
    for li, ri in zip(lb, rb):
        lhs[li] = rhs[ri] = next(letters)
    for li, ri in zip(lc, rc):
        lhs[li] = rhs[ri] = next(letters)
    for i in range(lhs_ndim):
        if lhs[i] is None:
            lhs[i] = next(letters)
    for i in range(rhs_ndim):
        if rhs[i] is None:
            rhs[i] = next(letters)
    out = ([lhs[i] for i in lb] +
           [lhs[i] for i in range(lhs_ndim) if i not in lb and
            i not in lc] +
           [rhs[i] for i in range(rhs_ndim) if i not in rb and
            i not in rc])
    return f"{''.join(lhs)},{''.join(rhs)}->{''.join(out)}"


def convert_jaxpr(closed_jaxpr, input_names: List[str],
                  graph_name: str = "main",
                  opset_version: int = 13) -> bytes:
    """Build ONNX ModelProto bytes from a closed jaxpr."""
    jaxpr = closed_jaxpr.jaxpr
    namer = _Namer()
    nodes: List[bytes] = []
    initializers: List[bytes] = []
    const_count = 0

    def add_const(arr: np.ndarray) -> str:
        nonlocal const_count
        const_count += 1
        name = f"const{const_count}"
        initializers.append(P.tensor_proto(name, _np(arr)))
        return name

    # graph inputs
    inputs = []
    for name, var in zip(input_names, jaxpr.invars):
        namer.names[var] = name
        inputs.append(P.value_info(name, var.aval.dtype, var.aval.shape))
    # captured consts become initializers
    for var, val in zip(jaxpr.constvars, closed_jaxpr.consts):
        cname = add_const(_np(val))
        namer.names[var] = cname

    from jax._src.core import Literal as _Literal

    def ref(atom) -> str:
        if isinstance(atom, _Literal):
            return add_const(_np(atom.val))
        return namer.of(atom)

    def emit(op, ins, outs, **attrs):
        nodes.append(P.node(op, ins, outs,
                            name=f"{op}_{len(nodes)}", attrs=attrs))

    def inline(eqn):
        """Inline a wrapped sub-jaxpr (custom_jvp/vjp, pjit, remat):
        bind its invars to the outer input names, walk its equations,
        then alias the outer outputs to the inner result names."""
        pp = eqn.params
        inner = pp.get("call_jaxpr", pp.get("jaxpr"))
        consts = []
        if hasattr(inner, "jaxpr"):  # ClosedJaxpr
            consts = inner.consts
            inner = inner.jaxpr
        for var, val in zip(inner.constvars, consts):
            namer.names[var] = add_const(_np(val))
        outer_names = [ref(a) for a in eqn.invars]
        # custom_jvp_call may carry leading const operands; align tails
        n = len(inner.invars)
        for var, nm in zip(inner.invars, outer_names[-n:]):
            namer.names[var] = nm
        for sub in inner.eqns:
            process(sub)
        for outer_var, inner_out in zip(eqn.outvars, inner.outvars):
            namer.names[outer_var] = ref(inner_out)

    def process(eqn):
        prim = eqn.primitive.name
        ins = [ref(a) for a in eqn.invars]
        outs = [namer.of(v) for v in eqn.outvars]
        pp = eqn.params
        if prim == "dot_general":
            ((lc, rc), (lb, rb)) = pp["dimension_numbers"]
            lhs, rhs = eqn.invars
            if not lb and not rb and lc == (lhs.aval.ndim - 1,) and \
                    rc == (0,):
                emit("MatMul", ins, outs)
            else:
                # batched / general contraction (attention einsums):
                # ONNX Einsum (opset 12+) takes the exact equation
                eq = _einsum_equation(lhs.aval.ndim, rhs.aval.ndim,
                                      pp["dimension_numbers"])
                emit("Einsum", ins, outs, equation=eq)
        elif prim in ("add", "add_any"):
            emit("Add", ins, outs)
        elif prim == "sub":
            emit("Sub", ins, outs)
        elif prim == "mul":
            emit("Mul", ins, outs)
        elif prim == "div":
            emit("Div", ins, outs)
        elif prim == "max":
            emit("Max", ins, outs)
        elif prim == "min":
            emit("Min", ins, outs)
        elif prim == "tanh":
            emit("Tanh", ins, outs)
        elif prim == "logistic":
            emit("Sigmoid", ins, outs)
        elif prim == "exp":
            emit("Exp", ins, outs)
        elif prim == "log":
            emit("Log", ins, outs)
        elif prim == "erf":
            emit("Erf", ins, outs)
        elif prim == "sqrt":
            emit("Sqrt", ins, outs)
        elif prim == "rsqrt":
            emit("Sqrt", ins, [outs[0] + "_sqrt"])
            emit("Reciprocal", [outs[0] + "_sqrt"], outs)
        elif prim == "neg":
            emit("Neg", ins, outs)
        elif prim == "abs":
            emit("Abs", ins, outs)
        elif prim == "pow":
            emit("Pow", ins, outs)
        elif prim == "integer_pow":
            expo = add_const(np.asarray(float(pp["y"]), np.float32))
            emit("Pow", [ins[0], expo], outs)
        elif prim == "reduce_sum":
            emit("ReduceSum",
                 [ins[0], add_const(np.asarray(pp["axes"], np.int64))],
                 outs, keepdims=0)
        elif prim == "reduce_max":
            # at opset 13 ReduceMax takes axes as an ATTRIBUTE (the
            # axes-input form is opset 18+); ReduceSum moved to the
            # input form at 13
            emit("ReduceMax", [ins[0]], outs,
                 axes=[int(a) for a in pp["axes"]], keepdims=0)
        elif prim == "reshape":
            shape = add_const(np.asarray(pp["new_sizes"], np.int64))
            emit("Reshape", [ins[0], shape], outs)
        elif prim == "squeeze":
            axes = add_const(np.asarray(pp["dimensions"], np.int64))
            emit("Squeeze", [ins[0], axes], outs)
        elif prim == "transpose":
            emit("Transpose", ins, outs, perm=list(pp["permutation"]))
        elif prim == "broadcast_in_dim":
            # ONNX broadcasting handles trailing-aligned shapes; emit an
            # explicit Expand through a reshape that inserts size-1 dims
            # at the mapped positions
            out_shape = pp["shape"]
            bdims = pp["broadcast_dimensions"]
            inter = [1] * len(out_shape)
            for src_i, dst_i in enumerate(bdims):
                inter[dst_i] = eqn.invars[0].aval.shape[src_i] \
                    if eqn.invars[0].aval.shape else 1
            rs = add_const(np.asarray(inter, np.int64))
            emit("Reshape", [ins[0], rs], [outs[0] + "_rs"])
            ex = add_const(np.asarray(out_shape, np.int64))
            emit("Expand", [outs[0] + "_rs", ex], outs)
        elif prim == "conv_general_dilated":
            dn = pp["dimension_numbers"]
            if dn.lhs_spec != tuple(range(len(dn.lhs_spec))):
                raise NotImplementedError(
                    "onnx export supports NCHW convolutions only")
            if any(d != 1 for d in pp.get("lhs_dilation", ())):
                raise NotImplementedError(
                    "onnx export: transposed/input-dilated convolution "
                    "(lhs_dilation != 1) is not supported — it would "
                    "silently export as a plain Conv")
            pads = pp["padding"]
            emit("Conv", ins, outs,
                 strides=list(pp["window_strides"]),
                 dilations=list(pp["rhs_dilation"]),
                 group=int(pp["feature_group_count"]),
                 pads=[p[0] for p in pads] + [p[1] for p in pads])
        elif prim in ("custom_jvp_call", "custom_vjp_call",
                      "custom_jvp_call_jaxpr", "pjit", "jit", "remat",
                      "checkpoint", "closed_call", "core_call"):
            # activations (relu/gelu custom_jvp), jitted sublayers, and
            # remat blocks trace through their primal jaxpr: inline it
            inline(eqn)
        elif prim == "convert_element_type":
            onnx_dt = P.NP_TO_ONNX[np.dtype(pp["new_dtype"])]
            emit("Cast", ins, outs, to=onnx_dt)
        elif prim in ("stop_gradient", "copy"):
            emit("Identity", ins, outs)
        elif prim == "square":
            emit("Mul", [ins[0], ins[0]], outs)
        elif prim == "erfc":
            emit("Erf", ins, [outs[0] + "_erf"])
            one = add_const(np.asarray(1.0, eqn.invars[0].aval.dtype))
            emit("Sub", [one, outs[0] + "_erf"], outs)
        elif prim == "select_n":
            # boolean select: select_n(pred, on_false, on_true);
            # ONNX Where(cond, X, Y) = cond ? X : Y
            if len(ins) != 3:
                raise NotImplementedError(
                    "onnx export: select_n with >2 cases")
            emit("Where", [ins[0], ins[2], ins[1]], outs)
        elif prim in ("eq", "lt", "gt", "le", "ge", "ne"):
            op = {"eq": "Equal", "lt": "Less", "gt": "Greater",
                  "le": "LessOrEqual", "ge": "GreaterOrEqual"}.get(prim)
            if prim == "ne":
                emit("Equal", ins, [outs[0] + "_eq"])
                emit("Not", [outs[0] + "_eq"], outs)
            else:
                emit(op, ins, outs)
        elif prim == "and":
            emit("And", ins, outs)
        elif prim == "or":
            emit("Or", ins, outs)
        elif prim == "not":
            emit("Not", ins, outs)
        elif prim == "concatenate":
            emit("Concat", ins, outs, axis=int(pp["dimension"]))
        elif prim == "slice":
            starts = add_const(np.asarray(pp["start_indices"], np.int64))
            ends = add_const(np.asarray(pp["limit_indices"], np.int64))
            axes = add_const(np.arange(len(pp["start_indices"]),
                                       dtype=np.int64))
            strides = pp.get("strides") or \
                (1,) * len(pp["start_indices"])
            steps = add_const(np.asarray(strides, np.int64))
            emit("Slice", [ins[0], starts, ends, axes, steps], outs)
        elif prim == "iota":
            # static shape: materialize as an initializer
            vals = np.arange(pp["shape"][pp["dimension"]])
            arr = np.broadcast_to(
                vals.reshape([-1 if i == pp["dimension"] else 1
                              for i in range(len(pp["shape"]))]),
                pp["shape"]).astype(np.dtype(pp["dtype"]))
            namer.names[eqn.outvars[0]] = add_const(arr)
        elif prim == "gather":
            dn = pp["dimension_numbers"]
            operand, start = eqn.invars
            idx_ndim = start.aval.ndim
            take_axis0 = (
                tuple(dn.collapsed_slice_dims) == (0,) and
                tuple(dn.start_index_map) == (0,) and
                not getattr(dn, "operand_batching_dims", ()) and
                tuple(pp["slice_sizes"]) ==
                (1,) + tuple(operand.aval.shape[1:]) and
                start.aval.shape[-1] == 1)
            if not take_axis0:
                raise NotImplementedError(
                    "onnx export: only axis-0 take/embedding-lookup "
                    f"gathers are supported (got {dn})")
            # drop the trailing index-vector dim, then Gather(axis=0)
            idx_shape = add_const(np.asarray(start.aval.shape[:-1],
                                             np.int64))
            emit("Reshape", [ins[1], idx_shape], [outs[0] + "_idx"])
            emit("Gather", [ins[0], outs[0] + "_idx"], outs, axis=0)
        else:
            raise NotImplementedError(
                f"onnx export: unsupported primitive {prim!r}; supported "
                "scope is the matmul/conv/elementwise/activation family")

    for eqn in jaxpr.eqns:
        process(eqn)

    outputs = [P.value_info(ref(v), v.aval.dtype, v.aval.shape)
               for v in jaxpr.outvars]
    g = P.graph(nodes, graph_name, inputs, outputs, initializers)
    return P.model(g, opset_version=opset_version)
