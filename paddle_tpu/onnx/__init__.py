"""ONNX export (reference: python/paddle/onnx/__init__.py __all__:
export — a thin wrapper over the paddle2onnx converter).

The reference imports paddle2onnx lazily and fails with a clear message
when it's absent; same contract here. When the ``onnx`` package is
available, a traced Program is converted directly (matmul/add/relu-class
graphs) — enough for smoke interop; complex programs should ship the
StableHLO artifact (paddle_tpu.static.save_inference_model), which is the
native serving format on TPU.
"""

from __future__ import annotations

__all__ = ["export"]


def export(layer, path: str, input_spec=None, opset_version: int = 9,
           **configs) -> None:
    """reference: paddle.onnx.export (onnx/export.py)."""
    try:
        import paddle2onnx  # noqa: F401
    except ImportError:
        raise ImportError(
            "paddle.onnx.export requires the paddle2onnx converter, which "
            "is not installed in this environment. Export a StableHLO "
            "artifact instead: paddle_tpu.static.save_inference_model"
            "(path, input_spec, layer=layer) — the TPU-native serving "
            "format loadable by paddle_tpu.inference.Predictor.") from None
    raise NotImplementedError(
        "paddle2onnx conversion of traced XLA programs is not wired up")
