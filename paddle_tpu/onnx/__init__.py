"""ONNX export (reference: python/paddle/onnx/__init__.py __all__:
export — a thin wrapper over the paddle2onnx converter; onnx/export.py
actually converts).

TPU-native: the traced artifact is a jaxpr; the supported primitive set
(matmul/conv/elementwise/activation — what Linear/Conv/MLP inference
graphs lower to) converts to a standard ONNX ModelProto. The file is
written with a hand-encoded protobuf writer (this image has no ``onnx``
package), so export works everywhere; the bytes load in
onnx/onnxruntime/netron. Complex programs (scan RNNs, attention with
reduce_window pooling, control flow) should ship the StableHLO artifact
(paddle_tpu.static.save_inference_model) — the native serving format.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

__all__ = ["export"]


def export(layer, path: str, input_spec: Optional[Sequence] = None,
           opset_version: int = 13, **configs) -> str:
    """reference: paddle.onnx.export(layer, path, input_spec) — writes
    ``path + '.onnx'`` and returns that filename."""
    import jax
    import jax.numpy as jnp

    from ..autograd.engine import no_grad
    from ..core.enforce import InvalidArgumentError
    from ..nn.layer import Layer
    from ..tensor import Tensor
    from ._convert import convert_jaxpr

    if input_spec is None:
        raise InvalidArgumentError(
            "paddle.onnx.export requires input_spec (static shapes are "
            "part of the traced program)")

    names: List[str] = []
    examples = []
    for i, spec in enumerate(input_spec):
        if any(d is None or (isinstance(d, int) and d < 0)
               for d in spec.shape):
            raise InvalidArgumentError(
                "paddle.onnx.export needs fully static input shapes "
                f"(got {tuple(spec.shape)}); dynamic dims would be baked "
                "in as the tracing placeholder")
        shape = tuple(int(d) for d in spec.shape)
        dtype = getattr(spec, "dtype", "float32")
        names.append(getattr(spec, "name", None) or f"x{i}")
        examples.append(jnp.zeros(shape, dtype))

    was_training = bool(getattr(layer, "training", False))
    if isinstance(layer, Layer):
        layer.eval()

    def fn(*xs):
        with no_grad():
            out = layer(*[Tensor(x) for x in xs])
        leaves = jax.tree_util.tree_leaves(out)
        raw = [v.value if isinstance(v, Tensor) else v for v in leaves]
        return raw[0] if len(raw) == 1 else tuple(raw)

    try:
        closed = jax.make_jaxpr(fn)(*examples)
    finally:
        if isinstance(layer, Layer) and was_training:
            layer.train()
    data = convert_jaxpr(closed, names,
                         graph_name=type(layer).__name__,
                         opset_version=opset_version)
    # when the real onnx package exists, validate before writing
    try:
        import onnx as _onnx
        _onnx.checker.check_model(_onnx.load_from_string(data))
    except ImportError:
        pass
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(data)
    return out_path
