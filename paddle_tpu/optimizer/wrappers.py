"""Optimizer wrappers: EMA, ModelAverage, Lookahead.

Reference parity: python/paddle/fluid/optimizer.py
(ExponentialMovingAverage:3882, ModelAverage:3573, LookaheadOptimizer:5969).
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..tensor import Parameter, Tensor


class ExponentialMovingAverage:
    """EMA of parameters; apply()/restore() swaps them in for eval
    (reference: fluid/optimizer.py:3882)."""

    def __init__(self, parameters_or_layer, decay: float = 0.999,
                 thres_steps=None):
        if hasattr(parameters_or_layer, "parameters"):
            self._params = list(parameters_or_layer.parameters())
        else:
            self._params = list(parameters_or_layer)
        self._decay = decay
        self._shadow: Dict[int, jnp.ndarray] = {
            id(p): jnp.array(p.value, copy=True) for p in self._params}
        self._backup: Dict[int, jnp.ndarray] = {}
        self._step = 0

    def update(self) -> None:
        self._step += 1
        d = min(self._decay, (1 + self._step) / (10 + self._step))
        for p in self._params:
            s = self._shadow[id(p)]
            self._shadow[id(p)] = d * s + (1.0 - d) * p.value

    def apply(self, restore: bool = True) -> None:
        for p in self._params:
            self._backup[id(p)] = p.value
            p.value = self._shadow[id(p)].astype(p.dtype)

    def restore(self) -> None:
        for p in self._params:
            if id(p) in self._backup:
                p.value = self._backup.pop(id(p))

    @contextlib.contextmanager
    def apply_guard(self):
        self.apply()
        try:
            yield
        finally:
            self.restore()

    def state_dict(self):
        return {f"shadow_{i}": Tensor(self._shadow[id(p)])
                for i, p in enumerate(self._params)} | {
                    "step": self._step}

    def set_state_dict(self, state):
        self._step = int(state.get("step", 0))
        for i, p in enumerate(self._params):
            v = state.get(f"shadow_{i}")
            if v is not None:
                self._shadow[id(p)] = jnp.asarray(
                    v.value if isinstance(v, Tensor) else v)


class ModelAverage:
    """Sliding-window parameter average
    (reference: fluid/optimizer.py:3573)."""

    def __init__(self, average_window_rate: float = 0.15,
                 parameters: Optional[List[Parameter]] = None,
                 min_average_window: int = 10000,
                 max_average_window: int = 10000):
        self._params = list(parameters or [])
        self._rate = average_window_rate
        self._min_w = min_average_window
        self._max_w = max_average_window
        self._sum = {id(p): jnp.zeros_like(p.value) for p in self._params}
        self._count = 0
        self._backup: Dict[int, jnp.ndarray] = {}

    def step(self) -> None:
        self._count += 1
        for p in self._params:
            self._sum[id(p)] = self._sum[id(p)] + p.value
        window = max(self._min_w, min(self._max_w,
                                      int(self._count * self._rate) or 1))
        if self._count > window:
            # decay old contributions geometrically
            scale = window / self._count
            for p in self._params:
                self._sum[id(p)] = self._sum[id(p)] * scale
            self._count = window

    def apply(self) -> None:
        for p in self._params:
            self._backup[id(p)] = p.value
            p.value = (self._sum[id(p)] / max(self._count, 1)).astype(
                p.dtype)

    def restore(self) -> None:
        for p in self._params:
            if id(p) in self._backup:
                p.value = self._backup.pop(id(p))

    @contextlib.contextmanager
    def apply_guard(self):
        self.apply()
        try:
            yield
        finally:
            self.restore()


class Lookahead:
    """Lookahead wrapper: slow weights track fast weights every k steps
    (reference: fluid/optimizer.py:5969 LookaheadOptimizer)."""

    def __init__(self, inner_optimizer, alpha: float = 0.5, k: int = 5):
        self.inner = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._slow: Optional[Dict[int, jnp.ndarray]] = None
        self._steps = 0

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def step(self) -> None:
        params = self.inner._parameter_list or []
        if self._slow is None:
            self._slow = {id(p): jnp.array(p.value, copy=True)
                          for p in params}
        self.inner.step()
        self._steps += 1
        if self._steps % self.k == 0:
            for p in params:
                slow = self._slow[id(p)] + self.alpha * (
                    p.value - self._slow[id(p)])
                self._slow[id(p)] = slow
                p.value = slow.astype(p.dtype)

    def clear_grad(self):
        self.inner.clear_grad()

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
