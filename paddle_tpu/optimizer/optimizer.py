"""Optimizers.

Reference parity: python/paddle/optimizer/ (Optimizer base, SGD, Momentum,
Adagrad, Adadelta, RMSProp, Adam, AdamW, Adamax, Lamb) and
operators/optimizers/ kernels (sgd_op, momentum_op, adam_op, lamb_op,
lars_momentum_op).

Design: each optimizer's update rule is a PURE function over
(param, grad, state, lr) so one implementation serves both the eager
``step()`` path (paddle-style: reads Parameter.grad, mutates values) and the
functional ``apply_gradients`` path used inside jitted/pjit-sharded train
steps — the same way the reference shares optimizer op kernels between
dygraph and static modes.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.enforce import InvalidArgumentError
from ..tensor import Parameter, Tensor
from .clip import GradClipBase
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip: Optional[GradClipBase] = None,
                 name=None, multi_precision: bool = False):
        if parameters is not None and isinstance(parameters, Parameter):
            raise InvalidArgumentError("parameters must be a list")
        self._learning_rate = learning_rate
        self._parameter_list = list(parameters) if parameters is not None \
            else None
        self._weight_decay = self._parse_wd(weight_decay)
        # L1Decay adds coeff*sign(w) instead of coeff*w (paddle.regularizer)
        self._wd_mode = getattr(weight_decay, "mode", "l2")
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        # per-param slot state, keyed by parameter name/index
        self._state: Dict[str, Dict[str, Any]] = {}
        self._global_step = 0
        self._param_names: Dict[int, str] = {}

    # -- helpers --------------------------------------------------------------

    @staticmethod
    def _parse_wd(weight_decay):
        if weight_decay is None:
            return 0.0
        if isinstance(weight_decay, (int, float)):
            return float(weight_decay)
        # L2Decay-style object with a coeff attribute
        return float(getattr(weight_decay, "_coeff",
                             getattr(weight_decay, "coeff", 0.0)))

    def _param_name(self, p: Parameter, idx: int) -> str:
        if id(p) not in self._param_names:
            self._param_names[id(p)] = p.name or f"param_{idx}"
        return self._param_names[id(p)]

    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value: float) -> None:
        if isinstance(self._learning_rate, LRScheduler):
            raise InvalidArgumentError(
                "cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    @property
    def _lr_scheduler(self):
        return self._learning_rate if isinstance(
            self._learning_rate, LRScheduler) else None

    # -- pure update rule (override in subclasses) ----------------------------

    def _init_state(self, value: jax.Array) -> Dict[str, jax.Array]:
        return {}

    def _update(self, value, grad, state, lr, step):
        """Return (new_value, new_state). Must be pure/jit-safe."""
        raise NotImplementedError

    # -- eager path -----------------------------------------------------------

    def step(self) -> None:
        params = self._parameter_list
        if params is None:
            raise InvalidArgumentError(
                "Optimizer constructed without parameters; pass parameters= "
                "or use apply_gradients for the functional path")
        self._global_step += 1
        named = [(self._param_name(p, i), p) for i, p in enumerate(params)
                 if p is not None and p.trainable]
        grads = {n: p.grad.value for n, p in named if p.grad is not None}
        if self._grad_clip is not None:
            grads = self._grad_clip.apply(grads)
        lr = self.get_lr()
        live = [(n, p) for n, p in named if n in grads]
        for n, p in live:
            if n not in self._state:
                self._state[n] = self._init_state(p.value)
        new_p, new_s = self._apply_flat(
            [p.value for _, p in live], [grads[n] for n, _ in live],
            [self._state[n] for n, _ in live], lr, self._global_step)
        for (n, p), nv, ns in zip(live, new_p, new_s):
            p.value = nv
            self._state[n] = ns

    _decoupled_wd = False  # AdamW overrides
    # Elementwise _update rule => safe to run on one fused flat
    # buffer. Optimizers with per-tensor norms (LAMB/LARS) opt out.
    _elementwise_update = True
    # _init_state has Python side effects (e.g. Dpsgd's per-param noise-id
    # counter) => init() must call it exactly once per param, eagerly —
    # never under eval_shape/jit where it would trace twice or fold the
    # state into a cached constant.
    _stateful_slot_init = False

    def clear_grad(self) -> None:
        if self._parameter_list:
            for p in self._parameter_list:
                if p is not None:
                    p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None) -> None:
        """Eager convenience: backward + step (reference
        Optimizer.minimize)."""
        loss.backward()
        self.step()

    # -- functional path (jit/pjit) -------------------------------------------

    def init(self, params: Dict[str, jax.Array]) -> Dict[str, Any]:
        """Build the optimizer-state pytree for a params pytree. Slot
        leaves whose shape matches their param inherit the param's
        NamedSharding (moments must shard like the weight — pp/mp-sharded
        params with replicated moments would hold the FULL moment tree on
        every device; reference: sharding_optimizer.py shards slots with
        their params)."""
        flat, treedef = jax.tree_util.tree_flatten(params)
        jit_cache = {}

        def init_placed(p):
            sh = getattr(p, "sharding", None)
            if self._stateful_slot_init or \
                    not isinstance(sh, jax.sharding.NamedSharding):
                return self._init_state(p)
            # allocate each slot directly with its target sharding (a
            # zeros-then-reshard would transiently materialize the FULL
            # slot on one device — OOM for models that only fit sharded).
            # The jitted init is cached per (shape, dtype, sharding):
            # _init_state must be pure here (stateful optimizers set
            # _stateful_slot_init and take the eager path above).
            from jax.sharding import PartitionSpec
            shapes = jax.eval_shape(self._init_state, p)
            if not jax.tree_util.tree_leaves(shapes):
                return self._init_state(p)
            repl = jax.sharding.NamedSharding(sh.mesh, PartitionSpec())
            out_sh = jax.tree_util.tree_map(
                lambda s: sh if tuple(s.shape) == tuple(p.shape) else repl,
                shapes)
            key = (tuple(p.shape), str(p.dtype), sh)
            if key not in jit_cache:
                jit_cache[key] = jax.jit(self._init_state,
                                         out_shardings=out_sh)
            return jit_cache[key](p)

        states = [init_placed(v) for v in flat]
        return {"slots": jax.tree_util.tree_unflatten(treedef, states),
                "step": jnp.zeros((), jnp.int32)}

    def apply_gradients(self, params, grads, opt_state,
                        lr: Optional[Any] = None):
        """Pure update: (params, grads, state) -> (new_params, new_state)."""
        lr = self.get_lr() if lr is None else lr
        step = opt_state["step"] + 1
        if self._grad_clip is not None:
            flat_g, gdef = jax.tree_util.tree_flatten(grads)
            named = {str(i): g for i, g in enumerate(flat_g)}
            named = self._grad_clip.apply(named)
            flat_g = [named[str(i)] for i in range(len(flat_g))]
            grads = jax.tree_util.tree_unflatten(gdef, flat_g)

        flat_p, pdef = jax.tree_util.tree_flatten(params)
        # flatten_up_to (not tree_leaves) so a None grad stays a leaf in
        # its slot instead of vanishing and misaligning the zip
        flat_g = pdef.flatten_up_to(grads)
        flat_s = pdef.flatten_up_to(opt_state["slots"])
        new_p, new_s = self._apply_flat(flat_p, flat_g, flat_s, lr, step)
        return (jax.tree_util.tree_unflatten(pdef, new_p),
                {"slots": jax.tree_util.tree_unflatten(pdef, new_s),
                 "step": step})

    def _apply_flat(self, flat_p, flat_g, flat_s, lr, step):
        """Shared core of step()/apply_gradients: per-param _update calls,
        or — under FLAGS_fuse_optimizer — one concatenated update per
        (dtype, slot-dtypes) group."""
        new_p: list = [None] * len(flat_p)
        new_s: list = [None] * len(flat_p)

        def update_with_wd(v, g, s):
            decay_dir = v
            if self._weight_decay and self._wd_mode == "l1":
                import jax.numpy as _jnp
                decay_dir = _jnp.sign(v)
            if self._weight_decay and not self._decoupled_wd:
                g = g + self._weight_decay * decay_dir
            nv, ns = self._update(v, g, s, lr, step)
            if self._weight_decay and self._decoupled_wd:
                nv = nv - lr * self._weight_decay * decay_dir
            # a traced f32 lr must not widen low-precision params (bf16
            # value - f32 scalar promotes): updates keep the param dtype
            if hasattr(nv, "dtype") and nv.dtype != v.dtype:
                nv = nv.astype(v.dtype)
            return nv, ns

        def update_one(i, v, g, s):
            new_p[i], new_s[i] = update_with_wd(v, g, s)

        # Fused update: concatenate same-dtype params into one flat buffer
        # so the whole optimizer step is a handful of large elementwise
        # kernels instead of ~10 tiny ones per parameter (TPU-native
        # analog of the reference's coalesce_grad_tensor_pass +
        # fuse_optimizer_ops_pass; paddle/fluid/framework/ir/).
        from ..core.flags import get_flag
        fuse = (get_flag("fuse_optimizer") and self._elementwise_update
                and getattr(self, "_apply_decay_param_fun", None) is None)
        groups: Dict[Any, list] = {}
        for i, (v, g, s) in enumerate(zip(flat_p, flat_g, flat_s)):
            if g is None:
                new_p[i], new_s[i] = v, s
            elif fuse and all(s[k].shape == v.shape for k in s):
                key = (str(v.dtype),
                       tuple((k, str(s[k].dtype)) for k in sorted(s)))
                groups.setdefault(key, []).append(i)
            else:
                update_one(i, v, g, s)

        for key, idxs in groups.items():
            if len(idxs) == 1:
                i = idxs[0]
                update_one(i, flat_p[i], flat_g[i], flat_s[i])
                continue
            sizes = [int(np.prod(flat_p[i].shape)) for i in idxs]
            offs = list(np.cumsum(sizes)[:-1])
            cat_v = jnp.concatenate([flat_p[i].ravel() for i in idxs])
            cat_g = jnp.concatenate([flat_g[i].ravel() for i in idxs])
            cat_s = {k: jnp.concatenate([flat_s[i][k].ravel()
                                         for i in idxs])
                     for k in flat_s[idxs[0]]}
            nv, ns = update_with_wd(cat_v, cat_g, cat_s)
            for i, piece in zip(idxs, jnp.split(nv, offs)):
                new_p[i] = piece.reshape(flat_p[i].shape)
            split_s = {k: jnp.split(ns[k], offs) for k in ns}
            for j, i in enumerate(idxs):
                new_s[i] = {k: split_s[k][j].reshape(flat_p[i].shape)
                            for k in split_s}

        return new_p, new_s

    # -- state dict -----------------------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"global_step": self._global_step}
        for pname, slots in self._state.items():
            for sname, v in slots.items():
                out[f"{pname}.{sname}"] = Tensor(v)
        if self._lr_scheduler is not None:
            out["LR_Scheduler"] = self._lr_scheduler.state_dict()
        return out

    def set_state_dict(self, state: Dict[str, Any]) -> None:
        self._global_step = int(state.get("global_step", 0))
        if "LR_Scheduler" in state and self._lr_scheduler is not None:
            self._lr_scheduler.set_state_dict(state["LR_Scheduler"])
        for key, v in state.items():
            if key in ("global_step", "LR_Scheduler"):
                continue
            pname, _, sname = key.rpartition(".")
            arr = v.value if isinstance(v, Tensor) else jnp.asarray(v)
            self._state.setdefault(pname, {})[sname] = arr


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)

    def _update(self, value, grad, state, lr, step):
        return value - lr * grad.astype(value.dtype), state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_state(self, value):
        return {"velocity": jnp.zeros_like(value)}

    def _update(self, value, grad, state, lr, step):
        g = grad.astype(value.dtype)
        v = self._momentum * state["velocity"] + g
        if self._nesterov:
            new_value = value - lr * (g + self._momentum * v)
        else:
            new_value = value - lr * v
        return new_value, {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, value):
        return {"moment": jnp.full_like(value, self._init_acc)}

    def _update(self, value, grad, state, lr, step):
        g = grad.astype(value.dtype)
        m = state["moment"] + g * g
        new_value = value - lr * g / (jnp.sqrt(m) + self._epsilon)
        return new_value, {"moment": m}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = epsilon
        self._rho = rho

    def _init_state(self, value):
        return {"avg_squared_grad": jnp.zeros_like(value),
                "avg_squared_update": jnp.zeros_like(value)}

    def _update(self, value, grad, state, lr, step):
        g = grad.astype(value.dtype)
        asg = self._rho * state["avg_squared_grad"] + (1 - self._rho) * g * g
        update = g * jnp.sqrt(state["avg_squared_update"] + self._epsilon) \
            / jnp.sqrt(asg + self._epsilon)
        asu = self._rho * state["avg_squared_update"] + \
            (1 - self._rho) * update * update
        return value - lr * update, {"avg_squared_grad": asg,
                                     "avg_squared_update": asu}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _init_state(self, value):
        s = {"mean_square": jnp.zeros_like(value),
             "momentum": jnp.zeros_like(value)}
        if self._centered:
            s["mean_grad"] = jnp.zeros_like(value)
        return s

    def _update(self, value, grad, state, lr, step):
        g = grad.astype(value.dtype)
        ms = self._rho * state["mean_square"] + (1 - self._rho) * g * g
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
        else:
            mg = None
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * state["momentum"] + lr * g / denom
        new_state = {"mean_square": ms, "momentum": mom}
        if mg is not None:
            new_state["mean_grad"] = mg
        return value - mom, new_state


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _init_state(self, value):
        acc_dtype = jnp.float32 if self._multi_precision else value.dtype
        return {"moment1": jnp.zeros(value.shape, acc_dtype),
                "moment2": jnp.zeros(value.shape, acc_dtype)}

    def _update(self, value, grad, state, lr, step):
        acc_dtype = state["moment1"].dtype
        g = grad.astype(acc_dtype)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * g * g
        step_f = jnp.asarray(step, jnp.float32)
        bc1 = 1.0 - self._beta1 ** step_f
        bc2 = 1.0 - self._beta2 ** step_f
        m_hat = m / bc1
        v_hat = v / bc2
        upd = lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon)
        new_value = (value.astype(acc_dtype) - upd).astype(value.dtype)
        return new_value, {"moment1": m, "moment2": v}


class AdamW(Adam):
    """Decoupled weight decay (reference: optimizer/adamw.py)."""

    _decoupled_wd = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 apply_decay_param_fun=None, name=None, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         name)
        self._apply_decay_param_fun = apply_decay_param_fun


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _init_state(self, value):
        return {"moment": jnp.zeros_like(value),
                "inf_norm": jnp.zeros_like(value)}

    def _update(self, value, grad, state, lr, step):
        g = grad.astype(value.dtype)
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g))
        step_f = jnp.asarray(step, jnp.float32)
        lr_t = lr / (1.0 - self._beta1 ** step_f)
        new_value = value - lr_t * m / (u + self._epsilon)
        return new_value, {"moment": m, "inf_norm": u}


class Lamb(Optimizer):
    """Layer-wise adaptive moments for large-batch training
    (reference: optimizer/lamb.py, operators/optimizers/lamb_op)."""

    _elementwise_update = False  # per-param trust ratio uses tensor norms

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lamb_wd = lamb_weight_decay
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, value):
        return {"moment1": jnp.zeros_like(value, jnp.float32),
                "moment2": jnp.zeros_like(value, jnp.float32)}

    def _update(self, value, grad, state, lr, step):
        g = grad.astype(jnp.float32)
        v32 = value.astype(jnp.float32)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * g * g
        step_f = jnp.asarray(step, jnp.float32)
        m_hat = m / (1.0 - self._beta1 ** step_f)
        v_hat = v / (1.0 - self._beta2 ** step_f)
        r = m_hat / (jnp.sqrt(v_hat) + self._epsilon) + self._lamb_wd * v32
        w_norm = jnp.linalg.norm(v32)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_value = (v32 - lr * trust * r).astype(value.dtype)
        return new_value, {"moment1": m, "moment2": v}


class LarsMomentum(Optimizer):
    """LARS (reference: fluid/optimizer.py LarsMomentumOptimizer,
    operators/optimizers/lars_momentum_op.cu)."""

    _elementwise_update = False  # per-layer norms

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005, parameters=None,
                 grad_clip=None, epsilon=1e-9, name=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._epsilon = epsilon

    def _init_state(self, value):
        return {"velocity": jnp.zeros_like(value)}

    def _update(self, value, grad, state, lr, step):
        g = grad.astype(value.dtype)
        w_norm = jnp.linalg.norm(value.astype(jnp.float32))
        g_norm = jnp.linalg.norm(g.astype(jnp.float32))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._lars_coeff * w_norm /
            (g_norm + self._lars_wd * w_norm + self._epsilon), 1.0)
        v = self._momentum * state["velocity"] + lr * local_lr * (
            g + self._lars_wd * value)
        return value - v, {"velocity": v}


class Ftrl(Optimizer):
    """Follow-the-regularized-leader (reference: fluid/optimizer.py
    FtrlOptimizer, operators/optimizers/ftrl_op)."""

    def __init__(self, learning_rate=0.001, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _init_state(self, value):
        return {"squared": jnp.zeros_like(value),
                "linear": jnp.zeros_like(value)}

    def _update(self, value, grad, state, lr, step):
        g = grad.astype(value.dtype)
        sq, lin = state["squared"], state["linear"]
        new_sq = sq + g * g
        lp = -self._lr_power
        sigma = (new_sq ** lp - sq ** lp) / lr
        new_lin = lin + g - sigma * value
        pre = new_sq ** lp / lr + 2.0 * self._l2
        l1 = self._l1
        new_value = jnp.where(
            jnp.abs(new_lin) > l1,
            (jnp.sign(new_lin) * l1 - new_lin) / pre, 0.0
        ).astype(value.dtype)
        return new_value, {"squared": new_sq, "linear": new_lin}


class Dpsgd(Optimizer):
    """Differentially-private SGD: gradient + calibrated Gaussian noise
    (reference: fluid/optimizer.py DpsgdOptimizer,
    operators/optimizers/dpsgd_op — clip/batch/sigma parameters)."""

    # per-tensor clip norm + per-param noise draw: a fused concatenated
    # update would clip the GLOBAL norm and draw one noise vector,
    # changing the DP sensitivity bound (same reason Lamb/LARS opt out)
    _elementwise_update = False

    def __init__(self, learning_rate=0.001, clip=10.0, batch_size=16.0,
                 sigma=1.0, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, seed: int = 0, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._clip, self._batch, self._sigma = clip, batch_size, sigma
        self._seed = seed
        self._next_noise_id = 0

    _stateful_slot_init = True  # the noise-id counter below

    def _init_state(self, value):
        # a unique per-parameter id (assigned at slot-init order) folds
        # into the noise key so same-shaped params draw INDEPENDENT noise
        nid = self._next_noise_id
        self._next_noise_id += 1
        return {"noise_id": jnp.asarray(nid, jnp.int32)}

    def _update(self, value, grad, state, lr, step):
        g = grad.astype(jnp.float32)
        norm = jnp.sqrt(jnp.sum(g * g))
        scale = jnp.minimum(1.0, self._clip / jnp.maximum(norm, 1e-12))
        g = g * scale
        key = jax.random.fold_in(
            jax.random.fold_in(jax.random.key(self._seed),
                               jnp.asarray(step, jnp.int32)),
            state["noise_id"])
        noise = jax.random.normal(key, g.shape, jnp.float32) * (
            self._clip * self._sigma / self._batch)
        new_value = (value.astype(jnp.float32) -
                     lr * (g + noise)).astype(value.dtype)
        return new_value, state


class DecayedAdagrad(Optimizer):
    """Adagrad with decaying accumulator (reference: fluid/optimizer.py
    DecayedAdagradOptimizer, operators/optimizers/decayed_adagrad_op)."""

    def __init__(self, learning_rate=0.001, decay=0.95, epsilon=1e-6,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._decay, self._epsilon = decay, epsilon

    def _init_state(self, value):
        return {"moment": jnp.zeros_like(value)}

    def _update(self, value, grad, state, lr, step):
        g = grad.astype(value.dtype)
        m = self._decay * state["moment"] + (1 - self._decay) * g * g
        new_value = value - lr * g / (jnp.sqrt(m) + self._epsilon)
        return new_value, {"moment": m}


class Rprop(Optimizer):
    """Resilient backprop: sign-based per-weight step sizes (reference:
    paddle Rprop optimizer family)."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 etas=(0.5, 1.2), parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_minus, self._eta_plus = etas

    def _init_state(self, value):
        return {"prev_grad": jnp.zeros_like(value),
                "step_size": jnp.full_like(
                    value, float(self.get_lr()))}

    def _update(self, value, grad, state, lr, step):
        g = grad.astype(value.dtype)
        prev, sz = state["prev_grad"], state["step_size"]
        sign = jnp.sign(g * prev)
        sz = jnp.clip(
            jnp.where(sign > 0, sz * self._eta_plus,
                      jnp.where(sign < 0, sz * self._eta_minus, sz)),
            self._lr_min, self._lr_max)
        g_eff = jnp.where(sign < 0, 0.0, g)
        new_value = value - jnp.sign(g_eff) * sz
        return new_value, {"prev_grad": g_eff, "step_size": sz}
