"""paddle_tpu.optimizer (reference parity: python/paddle/optimizer/)."""

from . import lr
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue
from .dgc import DGCMomentum
from .optimizer import (SGD, Adadelta, Adagrad, Adam, Adamax, AdamW,
                        DecayedAdagrad, Dpsgd, Ftrl, Lamb, LarsMomentum,
                        Momentum, Optimizer, RMSProp, Rprop)
