"""Deep Gradient Compression momentum optimizer.

Reference parity: operators/optimizers/dgc_momentum_op.cc +
meta_optimizers/dgc_optimizer.py (+ the external dgc lib). Algorithm
(Lin et al., "Deep Gradient Compression"): momentum correction + local
gradient accumulation + top-k sparsification with error feedback, with
a warmup of vanilla momentum and a sparsity ramp-up schedule.

TPU-native notes: on GPU clusters DGC's payoff is ethernet bandwidth;
sparse allreduce does not map onto ICI collectives, so the compressed
gradient is exchanged as a masked dense tensor — full algorithmic
semantics (the part that changes convergence), with the ICI fabric
covering bandwidth. The top-k threshold is estimated from a strided
sample like the reference's sampling estimator, so the update stays
jit-safe (no data-dependent k).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from .optimizer import Momentum

_SAMPLE_CAP = 4096


def _threshold(v_abs: jax.Array, sparsity: jax.Array) -> jax.Array:
    """Estimate the |v| threshold keeping ~(1-sparsity) of entries,
    from a strided sample (reference: dgc lib sampling estimator)."""
    flat = v_abs.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    stride = max(1, n // _SAMPLE_CAP)
    sample = flat[::stride]
    return jnp.quantile(sample, jnp.clip(sparsity, 0.0, 1.0))


class DGCMomentum(Momentum):
    """Momentum with deep-gradient-compression semantics.

    Before ``rampup_begin_step`` it is exactly ``Momentum``; after, each
    step accumulates a velocity ``u`` and an error-feedback buffer ``v``
    and applies only the top-magnitude fraction of ``v`` (per the
    ramped ``sparsity`` schedule), keeping the rest for later steps.
    """

    # top-k threshold is per-tensor — a fused flat buffer would compute
    # one global threshold and starve small-magnitude params
    _elementwise_update = False

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 rampup_begin_step: int = 0, rampup_step: int = 1,
                 sparsity: Sequence[float] = (0.999,),
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, momentum, parameters,
                         use_nesterov, weight_decay, grad_clip, name, **kw)
        self._rampup_begin = int(rampup_begin_step)
        self._rampup_step = max(1, int(rampup_step))
        self._sparsity = tuple(float(s) for s in sparsity)

    def _init_state(self, value):
        return {"velocity": jnp.zeros_like(value),
                "u": jnp.zeros_like(value),
                "v": jnp.zeros_like(value)}

    def sparsity_at(self, step) -> jax.Array:
        """Ramp through the sparsity list over rampup_step steps
        (reference: dgc_configs sparsity ramp)."""
        levels = jnp.asarray(self._sparsity, jnp.float32)
        if len(self._sparsity) == 1:
            return levels[0]
        pos = (jnp.asarray(step, jnp.float32) - self._rampup_begin) \
            / self._rampup_step * (len(self._sparsity) - 1)
        return jnp.interp(jnp.clip(pos, 0.0, len(self._sparsity) - 1),
                          jnp.arange(len(self._sparsity),
                                     dtype=jnp.float32), levels)

    def _update(self, value, grad, state, lr, step):
        g = grad.astype(value.dtype)
        m = jnp.asarray(self._momentum, value.dtype)

        def dense(_):
            vel = m * state["velocity"] + g
            if self._nesterov:
                nv = value - lr * (g + m * vel)
            else:
                nv = value - lr * vel
            return nv, vel, state["u"], state["v"]

        def compressed(_):
            # momentum correction: velocity accumulates locally…
            u = m * state["u"] + g
            v = state["v"] + u
            # …and only the top-magnitude slice is applied this step.
            sp = self.sparsity_at(step).astype(jnp.float32)
            thr = _threshold(jnp.abs(v), sp).astype(value.dtype)
            # >= so uniform-magnitude tensors (thr == max|v|) still
            # apply instead of starving while v grows unboundedly
            mask = jnp.abs(v) >= thr
            applied = jnp.where(mask, v, jnp.zeros_like(v))
            new_v = jnp.where(mask, jnp.zeros_like(v), v)
            new_u = jnp.where(mask, jnp.zeros_like(u), u)
            nv = value - lr * applied
            return nv, state["velocity"], new_u, new_v

        if value.ndim == 0 or value.size <= 1:
            # scalars are never worth sparsifying
            nv, vel, u, v = dense(None)
        else:
            nv, vel, u, v = lax.cond(
                jnp.asarray(step) <= self._rampup_begin, dense,
                compressed, None)
        return nv, {"velocity": vel, "u": u, "v": v}
