"""Gradient clipping (reference: python/paddle/fluid/clip.py —
ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm)."""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp


class GradClipBase:
    def apply(self, grads: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        raise NotImplementedError

    __call__ = apply


class ClipGradByValue(GradClipBase):
    def __init__(self, max, min=None):  # noqa: A002
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def apply(self, grads):
        return {k: jnp.clip(g, self.min, self.max)
                for k, g in grads.items()}


class ClipGradByNorm(GradClipBase):
    """Per-tensor L2 norm clip."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def apply(self, grads):
        out = {}
        for k, g in grads.items():
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12),
                                1.0)
            out[k] = (g.astype(jnp.float32) * scale).astype(g.dtype)
        return out


class ClipGradByGlobalNorm(GradClipBase):
    """Global L2 norm clip across all grads (the hybrid-parallel-aware
    variant lives in distributed.fleet — it psums the squared norm over the
    model-parallel mesh axes first)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)

    def global_norm(self, grads):
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                 for g in grads.values())
        return jnp.sqrt(sq)

    def apply(self, grads):
        gnorm = self.global_norm(grads)
        scale = jnp.minimum(self.clip_norm / jnp.maximum(gnorm, 1e-12), 1.0)
        return {k: (g.astype(jnp.float32) * scale).astype(g.dtype)
                for k, g in grads.items()}
