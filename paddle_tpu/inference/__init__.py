"""paddle_tpu.inference — AOT-compiled serving predictor.

Reference parity: paddle/fluid/inference/ AnalysisPredictor
(api/analysis_predictor.cc Init:145, PrepareExecutor:312, ZeroCopyRun:889)
+ AnalysisConfig (api/paddle_analysis_config.h) + python/paddle/inference.

TPU-native: "analysis passes + TensorRT subgraphs" collapse into XLA's
AOT compile of the exported program; precision switching is a dtype cast
at load; zero-copy handles are device arrays.
"""

from .continuous_batching import (ContinuousBatchingEngine,  # noqa: F401
                                  DecodeRequest, PageAllocator,
                                  create_decode_engine)
from .page_ledger import (PageLedger,  # noqa: F401 (r18 observatory)
                          forecast_exhaustion)
from .speculative import (CallableDraft, ModelDraft,  # noqa: F401
                          NGramDraft, SpeculativeConfig)
from .fusion import fuse_conv_bn  # noqa: F401 (conv_bn_fuse_pass analog)
from .predictor import (Config, DataType, PlaceType, PrecisionType,
                        Predictor, PredictorPool, Tensor,
                        Tensor as InferTensor, create_predictor,
                        get_num_bytes_of_data_type, get_version)
