"""Speculative decoding over the paged continuous-batching engine.

Decode at b128 runs 1.63x off its own measured streaming floor
(PROFILE_DECODE.json): every emitted token re-reads the full weight
set and the KV prefix once. Speculative decoding amortizes that stream
over multiple tokens per step — a cheap DRAFT proposes ``k`` tokens,
the target model scores all ``k+1`` positions in ONE forward (the
chained-prefill ragged paged-attention path, models/gpt.py
``verify_step``), and the longest accepted prefix is emitted together
with one correction/bonus token. Greedy outputs are BIT-IDENTICAL to
the vanilla engine: acceptance is exact-match against the target's own
argmax, so a wrong draft costs only speed, never tokens.

This module holds the HOST half — draft sources and the config the
engine consumes (`ContinuousBatchingEngine(speculative=...)`); the
device half (verify forward + accept/resample math) lives in
models/gpt.py ``verify_step`` and nn/decode.py
``speculative_verify_tokens``. Draft sources are duck-typed::

    propose(histories, k) -> np.ndarray [len(histories), k] int32

where ``histories[i]`` is slot i's full token history (prompt +
generated, None for an empty slot). A draft's QUALITY moves the
acceptance rate; its correctness is irrelevant to the output stream —
which is why the n-gram source may guess from padded context and the
model source may truncate its context window without ceremony.

Paper basis: *Ragged Paged Attention* (PAPERS.md) — the multi-token
verify is exactly its q_len>1 ragged prefill over a non-empty slot;
fused multi-token steps echo *Operator Fusion for LLM Inference on the
Tensix Architecture* (PAPERS.md).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

__all__ = ["SpeculativeConfig", "NGramDraft", "ModelDraft",
           "CallableDraft", "SelfDraft", "as_spec_config",
           "device_draft_params"]


class NGramDraft:
    """Prompt-lookup drafting: no second model, no device work.

    For each sequence, take the longest suffix of length
    ``max_ngram .. min_ngram`` that re-occurs EARLIER in the history
    (most recent occurrence wins) and propose the ``k`` tokens that
    followed it there. Greedy decode of a fixed model is eventually
    periodic and real text is self-repeating (system prompts, code,
    quoted spans), so this accepts surprisingly often for zero draft
    cost. No match -> repeat the last token (a cheap guess; rejection
    only costs the step its speedup)."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError("need 1 <= min_ngram <= max_ngram")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def _lookup(self, h: np.ndarray, k: int) -> np.ndarray:
        n = len(h)
        out = np.full((k,), h[-1], np.int32)
        for g in range(min(self.max_ngram, n - 1), self.min_ngram - 1,
                       -1):
            pat = h[n - g:]
            # most recent earlier occurrence, vectorized: windows over
            # h[:n-1] end at e <= n-1 (the suffix itself, ending at n,
            # is excluded); this runs per active slot per engine step,
            # so it must not be a per-offset Python loop over the
            # whole history
            wins = np.lib.stride_tricks.sliding_window_view(
                h[:n - 1], g)
            hits = np.nonzero((wins == pat).all(axis=1))[0]
            if len(hits):
                e = int(hits[-1]) + g  # end (exclusive) of the match
                cont = h[e:e + k]
                out[:len(cont)] = cont
                out[len(cont):] = cont[-1]
                return out
        return out

    def propose(self, histories: Sequence[Optional[np.ndarray]],
                k: int) -> np.ndarray:
        out = np.zeros((len(histories), k), np.int32)
        for i, h in enumerate(histories):
            if h is None or len(h) == 0:
                continue
            out[i] = self._lookup(np.asarray(h, np.int32), k)
        return out


class ModelDraft:
    """A small causal LM drafting greedily for the target.

    The draft runs STATELESSLY over a fixed context window holding the
    last ``window`` tokens RIGHT-padded (real tokens at positions
    0..len-1, so causal attention never sees padding before a real
    token and drafting is EXACT while the history fits the window) —
    one jitted program scans ``k`` greedy steps, each a full no-cache
    forward, so the whole proposal is one device launch per engine
    step with no draft-side KV bookkeeping. Once the history exceeds
    the window it is truncated to its tail (positions restart at 0);
    that can only lower acceptance, never correctness — the verify
    step is the sole authority on emitted tokens. The draft's vocab
    must not exceed the target's (the engine clips defensively)."""

    def __init__(self, model, window: int = 64):
        model.eval()
        self.model = model
        self.window = int(window)
        self._jits = {}
        self._state = None

    def _build(self, k: int):
        import jax

        from ..autograd.engine import no_grad
        from ..nn.decode import sample_token
        from ..nn.layer import bind_state
        from ..tensor import Tensor

        model = self.model
        w = self.window

        def raw(t):
            return t.value if isinstance(t, Tensor) else t

        def draft(state, ctx, lens):
            import jax.numpy as jnp

            # single-device trace guard (same as GPT _generate_jit): a
            # live fleet group's hybrid-mesh activation constraints
            # must not reach the draft program
            from ..distributed.mp_layers import no_sharding_constraints

            b = ctx.shape[0]

            def body(carry, _):
                c, l = carry  # noqa: E741
                with bind_state(model, state), no_grad():
                    logits = raw(model.forward(Tensor(c)))
                last = jnp.take_along_axis(
                    logits, jnp.maximum(l - 1, 0)[:, None, None],
                    axis=1)[:, 0]
                nxt, _ = sample_token(last, 0.0)
                # grow in place until the window fills, then slide
                full = (l >= w)[:, None]
                slid = jnp.concatenate(
                    [c[:, 1:], jnp.zeros((b, 1), c.dtype)], axis=1)
                c = jnp.where(full, slid, c)
                pos = jnp.minimum(l, w - 1)
                c = c.at[jnp.arange(b), pos].set(nxt)
                return (c, jnp.minimum(l + 1, w)), nxt

            with no_sharding_constraints():
                _, toks = jax.lax.scan(body, (ctx, lens), None,
                                       length=k)
            return toks.swapaxes(0, 1)  # [B, k]

        return jax.jit(draft)

    def propose(self, histories: Sequence[Optional[np.ndarray]],
                k: int) -> np.ndarray:
        from ..nn.layer import functional_state

        w = self.window
        ctx = np.zeros((len(histories), w), np.int32)
        lens = np.zeros((len(histories),), np.int32)
        for i, h in enumerate(histories):
            if h is None or len(h) == 0:
                continue
            tail = np.asarray(h, np.int32)[-w:]
            ctx[i, :len(tail)] = tail
            lens[i] = len(tail)
        if k not in self._jits:
            self._jits[k] = self._build(k)
        if self._state is None:  # draft weights are frozen post-build
            self._state = functional_state(self.model)
        return np.asarray(self._jits[k](self._state, ctx, lens),
                          np.int32)


class SelfDraft:
    """Repeat the last emitted token ``k`` times. The degenerate
    prompt-lookup draft (NGramDraft's no-match fallback, promoted to
    the whole policy): free to compute, device-implementable as a
    broadcast, and surprisingly effective on runs of repeated tokens
    (whitespace, padding, looping greedy tails). Exists mostly as the
    simplest in-program draft source (r22) and as a bisection rung
    between "spec off" and "ngram"."""

    def propose(self, histories: Sequence[Optional[np.ndarray]],
                k: int) -> np.ndarray:
        out = np.zeros((len(histories), k), np.int32)
        for i, h in enumerate(histories):
            if h is None or len(h) == 0:
                continue
            out[i, :] = int(np.asarray(h)[-1])
        return out


class CallableDraft:
    """Adapter for a plain function ``fn(history, k) -> k tokens`` —
    tests use it to build adversarial (always-wrong) drafts that force
    rejection storms, benches to build oracle drafts."""

    def __init__(self, fn: Callable[[np.ndarray, int], Sequence[int]]):
        self.fn = fn

    def propose(self, histories: Sequence[Optional[np.ndarray]],
                k: int) -> np.ndarray:
        out = np.zeros((len(histories), k), np.int32)
        for i, h in enumerate(histories):
            if h is None or len(h) == 0:
                continue
            toks = np.asarray(self.fn(np.asarray(h, np.int32), k),
                              np.int32).reshape(-1)[:k]
            out[i, :len(toks)] = toks
            if len(toks) < k:
                out[i, len(toks):] = toks[-1] if len(toks) else 0
        return out


@dataclasses.dataclass
class SpeculativeConfig:
    """Engine-side speculative-decoding knobs.

    ``draft``: "ngram" (prompt lookup, no second model), a model layer
    (wrapped in ModelDraft), or any object with a ``propose`` method.
    ``k``: draft tokens per verify step — each step emits between 1
    and k+1 tokens. ``temperature``/``top_k``: sampling mode of the
    verify step (0.0 = the greedy serving mode, bit-identical to the
    vanilla engine; >0 uses residual-distribution resampling and is
    exact-in-distribution, not bit-pinned)."""

    k: int = 4
    draft: Any = "ngram"
    temperature: float = 0.0
    top_k: Optional[int] = None
    max_ngram: int = 3
    min_ngram: int = 1
    draft_window: int = 64
    seed: int = 0

    def __post_init__(self):
        if self.k < 1:
            raise ValueError("speculative k must be >= 1")

    def build_draft(self):
        d = self.draft
        if isinstance(d, str):
            if d == "self":
                return SelfDraft()
            if d != "ngram":
                raise ValueError(f"unknown draft source {d!r} "
                                 f"(expected 'ngram', 'self', a model "
                                 f"layer or a propose()-object)")
            return NGramDraft(self.max_ngram, self.min_ngram)
        if hasattr(d, "propose"):
            return d
        if callable(getattr(d, "forward", None)):
            return ModelDraft(d, window=self.draft_window)
        raise ValueError(f"cannot build a draft source from {d!r}")


def device_draft_params(draft) -> Optional[dict]:
    """Describe a draft source as a device-implementable program, or
    ``None`` if it has no device twin.

    The in-program inner loop (r22) moves drafting inside the macro
    ``while_loop``, so the draft must be expressible as pure array math
    over the slot's stored token history. NGramDraft has an exact
    gather-based twin (nn/decode.py ``ngram_draft_tokens``); SelfDraft
    is a broadcast. ModelDraft / CallableDraft run arbitrary host code
    and stay at the launch boundary — the engine falls back to the
    boundary-interleaved path for them."""
    if isinstance(draft, NGramDraft):
        return {"kind": "ngram", "max_ngram": draft.max_ngram,
                "min_ngram": draft.min_ngram}
    if isinstance(draft, SelfDraft):
        return {"kind": "self"}
    return None


def as_spec_config(spec) -> "SpeculativeConfig":
    """Coerce the engine's ``speculative=`` argument: a
    SpeculativeConfig passes through, an int means k with the n-gram
    draft, anything draft-shaped becomes the draft at default k."""
    if isinstance(spec, SpeculativeConfig):
        return spec
    if isinstance(spec, bool):
        raise ValueError("speculative must be a SpeculativeConfig, an "
                         "int k, or a draft source — not a bool")
    if isinstance(spec, int):
        return SpeculativeConfig(k=spec)
    return SpeculativeConfig(draft=spec)
