"""Eval-graph fusion passes for inference.

TPU-native analog of the reference's IR fusion passes
(paddle/fluid/framework/ir/conv_bn_fuse_pass.h ConvBNFusePass /
ConvEltwiseAddBNFusePass): a BatchNorm following a convolution folds
ALGEBRAICALLY into the conv weights at eval time. Measured on v5e
(ResNet-50 bf16 eval forward, scan-amortized): NO wall-time win — XLA
already fuses the eval-BN scale/shift into the surrounding elementwise
work, so unlike the reference's CUDA runtime the fold buys no kernel
launches here. Its value on this stack is parity, a smaller saved
artifact (53 fewer param/buffer groups for ResNet-50), and backends
whose compilers do not fuse.

Works on eager Layer trees (the reference pass works on the static IR):
- adjacent (Conv2D, BatchNorm2D) pairs inside nn.Sequential;
- sibling attribute pairs named conv/bn, conv1/bn1, ... on any Layer
  (the ResNet/MobileNet block convention).

Folding: W' = W * gamma / sqrt(var + eps) (per out-channel),
b' = beta + (b - mean) * gamma / sqrt(var + eps); the BN is replaced by
an identity. Valid only with running statistics — the pass refuses a
model left in train() mode.
"""

from __future__ import annotations

import re

import jax.numpy as jnp

from ..nn import Identity, Layer, Sequential
from ..nn.conv import Conv2D
from ..nn.norm import BatchNorm2D


def _fold_pair(conv: Conv2D, bn: BatchNorm2D) -> None:
    gamma = bn.weight.value if bn.weight is not None else \
        jnp.ones((bn._num_features,), jnp.float32)
    beta = bn.bias.value if bn.bias is not None else \
        jnp.zeros((bn._num_features,), jnp.float32)
    mean = bn._mean.value
    var = bn._variance.value
    scale = gamma / jnp.sqrt(var + bn._epsilon)
    w = conv.weight.value
    # conv weight layout is [out_c, in_c/groups, kh, kw] regardless of
    # data_format (the reference filter layout): scale per out-channel
    conv.weight.value = (w.astype(jnp.float32) *
                         scale.reshape(-1, 1, 1, 1)).astype(w.dtype)
    old_b = conv.bias.value if conv.bias is not None else 0.0
    new_b = beta + (old_b - mean) * scale
    if conv.bias is not None:
        conv.bias.value = new_b.astype(conv.bias.value.dtype)
    else:
        conv.bias = conv.create_parameter(
            (int(bn._num_features),), is_bias=True)
        conv.bias.value = new_b.astype(w.dtype)
        conv.bias.stop_gradient = True


def _foldable(conv, bn) -> bool:
    """conv output channels must be what the bn normalizes — rules out
    half the pre-activation (bn-before-conv) mismatches outright."""
    return (type(conv) is Conv2D and isinstance(bn, BatchNorm2D) and
            conv.weight.shape[0] == bn._num_features)


def _conv_bn_attr_pairs(layer: Layer):
    """(conv, bn, bn_attr_name) for the convN/bnN naming convention.

    Name adjacency assumes the POST-norm convention (conv feeds bn —
    the reference zoo's and this repo's blocks). A pre-activation block
    that reuses these names with bn BEFORE conv and equal channel
    counts cannot be distinguished by structure alone; such models
    should export with ``optimize=False``."""
    subs = dict(layer._sub_layers)
    for name, sub in list(subs.items()):
        m = re.fullmatch(r"conv(\d*)", name)
        if not m or not isinstance(sub, Conv2D):
            continue
        bn_name = f"bn{m.group(1)}"
        bn = subs.get(bn_name)
        if bn is not None and _foldable(sub, bn):
            yield sub, bn, bn_name


def fuse_conv_bn(model: Layer) -> int:
    """Fold every recognized Conv2D->BatchNorm2D pair in ``model``
    in-place; returns the number of folded pairs. The model must be in
    eval() mode (folding bakes the RUNNING statistics in)."""
    if model.training:
        raise RuntimeError(
            "fuse_conv_bn folds running statistics into the conv "
            "weights and is only valid in eval() mode; call "
            "model.eval() first (reference: conv_bn_fuse_pass runs on "
            "the inference program)")
    count = 0
    for layer, kind, a, b, bn_key in find_foldable_pairs(model):
        _fold_pair(a, b)
        if kind == "seq":
            layer._sub_layers[bn_key] = Identity()
        else:
            setattr(layer, bn_key, Identity())
        count += 1
    return count


def find_foldable_pairs(model: Layer):
    """Read-only scan for (parent, kind, conv, bn, bn_key) fold sites —
    lets callers (save_inference_model) check BEFORE paying a deepcopy."""
    # snapshot list: safe even if a caller folds (mutates) while iterating
    for layer in model.sublayers(include_self=True):
        # pattern 1: adjacent pairs inside a Sequential
        if isinstance(layer, Sequential):
            subs = list(layer._sub_layers.items())
            for (n1, a), (n2, b) in zip(subs, subs[1:]):
                if _foldable(a, b):
                    yield layer, "seq", a, b, n2
        # pattern 2: convN/bnN sibling attributes (block convention)
        else:
            for conv, bn, bn_name in _conv_bn_attr_pairs(layer):
                yield layer, "attr", conv, bn, bn_name


def fold_preserves_outputs(original: Layer, folded: Layer, example_inputs,
                           rtol: float = 3e-2) -> bool:
    """Numerically compare ``original`` vs ``folded`` eval forwards.

    ``example_inputs`` is one example (a list of input tensors) or a
    list of several — save_inference_model passes 3 independent random
    draws. The name-based convN/bnN pairing cannot structurally
    distinguish a pre-activation block (bn BEFORE conv, equal channel
    counts) from the post-norm convention it assumes — a wrong fold
    there is algebraically different, not subtly off. The tolerance is
    scaled to each output's OWN magnitude (r4 advisor: a denom clamped
    to 1.0 turned rtol into a 0.03 ABSOLUTE tolerance, wide enough to
    pass a wrong fold of small-magnitude outputs such as post-softmax
    probabilities). Used by save_inference_model to refuse a bad fold."""
    import numpy as np

    from ..tensor import Tensor

    def is_single(ex):
        return not ex or not isinstance(ex[0], (tuple, list))

    batches = [example_inputs] if is_single(example_inputs) \
        else example_inputs

    def run(m, ex):
        outs = m(*ex)
        leaves = outs if isinstance(outs, (tuple, list)) else [outs]
        return [np.asarray((o.value if isinstance(o, Tensor) else o),
                           dtype=np.float32) for o in leaves]

    for ex in batches:
        ref, got = run(original, ex), run(folded, ex)
        if len(ref) != len(got):
            return False
        for r, g in zip(ref, got):
            if r.shape != g.shape:
                return False
            # per-element relative check with a floor scaled to the
            # output's OWN magnitude: small-magnitude heads
            # (probabilities, normalized scores) get a proportionally
            # tight bound instead of the old 0.03 absolute one, while
            # large-range outputs (logits) keep the per-element
            # tightness a single tensor-wide max bound would lose
            scale = max(float(np.max(np.abs(r))), 1e-6)
            denom = np.maximum(np.abs(r), 0.1 * scale)
            if not np.all(np.abs(r - g) / denom <= rtol):
                return False
    return True
