"""Page ledger: per-page event forensics for the paged KV pool (r18).

PR 10 made *latency* attributable (the span was the unit); this module
makes *memory* attributable — the page is the unit, exactly as in the
Ragged Paged Attention layout the allocator books. Every page event
the `PageAllocator` (and the engine's spill/restore device IO) performs
is appended to a BOUNDED ring with its owner, the engine step it
happened on, and the reason the engine was touching pages at the time
(admit / done / deadline / stalled / spec_rollback / macro_grow —
the r19 multi-step launch's reservation→page growth — / dedup_hit —
the r23 cross-request fold that releases a content-duplicate page
and moves the shared one to a ("dedup", key) owner — / close / ...).

What this buys:

- **Forensics, not counts**: ``check_no_leak`` used to say *how many*
  pages dangle; with a ledger attached it dumps each dangling page's
  ownership history (who allocated it, on which step, why, and every
  transfer since) — the difference between "3 pages leaked" and "page
  7 was alloc'd by request 12 at step 41 during admit and transferred
  to the prefix cache, which never released it".
- **Reconciliation**: the ledger maintains its own live ownership view
  from the event stream alone; ``reconcile(allocator)`` cross-checks
  it against the allocator's books. A mismatch means some code path
  moved pages without going through the allocator — the class of bug
  no leak counter can localize. The chaos harness asserts this per
  replica after drain (invariant 5).
- **Capacity timeline**: ``PageAllocator.occupancy()`` breaks the pool
  into owner classes (inflight / prefix-device / reserved / free, which
  sum to the pool size by construction); the engine stamps it into the
  step-timeline ring, and ``forecast_exhaustion`` turns ring deltas
  into an EWMA time-to-exhaustion estimate — the headroom signal the
  autoscaler actuator (ROADMAP 3a) and KV-shipping (item 1) need.

Bounded memory throughout: the event ring is a fixed-size deque, the
per-page history keeps the last few events per page (pages are bounded
by the pool), and the live ownership dicts are bounded by live owners.
The plane is BEHAVIOR-NEUTRAL: it only records host-side bookkeeping
the allocator already performs — greedy outputs are bit-identical
ledger on/off (pinned by tests/test_memory_observer.py).
"""

from __future__ import annotations

import collections
import contextlib
import time
from typing import Any, Dict, Hashable, List, Optional, Sequence

__all__ = ["PageLedger", "forecast_exhaustion"]

# the event vocabulary (ISSUE r18): allocator-side events plus the
# engine's spill/restore/splice device-IO events
EVENT_KINDS = ("alloc", "reserve", "alloc_reserved", "release", "free",
               "transfer", "spill", "restore", "splice")


def _fmt_owner(owner: Hashable) -> Any:
    """JSON-safe owner spelling: ints pass through (request ids),
    everything else (("prefix", b"...") tuples, strings) reprs."""
    if owner is None or isinstance(owner, (int, str)):
        return owner
    return repr(owner)


class PageLedger:
    """Bounded ring of page events plus a live ownership shadow.

    The allocator calls ``record`` after every successful mutation;
    the engine sets ``step`` at the top of each step and threads the
    REASON for a page operation through the ``why`` context manager
    (``with ledger.why("deadline", req_id=3): allocator.free(3)``), so
    every event says not just *what* moved but *why the engine was
    moving pages at that moment*.

    ``events`` hold plain JSON-safe dicts, so the ring tail travels in
    flight bundles and the ``capacity`` op without conversion."""

    def __init__(self, capacity: int = 1024, page_history: int = 8):
        self.capacity = max(1, int(capacity))
        self.ring: "collections.deque" = collections.deque(
            maxlen=self.capacity)
        self.seq = 0                 # lifetime event count
        self.dropped_total = 0       # events that rolled off the ring
        self.events_by_kind: Dict[str, int] = {}
        # last few events per page (bounded: pool size x page_history)
        self._page_history = max(1, int(page_history))
        self._page_hist: Dict[int, "collections.deque"] = {}
        # live ownership shadow, derived from the event stream ONLY —
        # reconcile() cross-checks it against the allocator's books
        self._live: Dict[Hashable, int] = {}
        self._reserved: Dict[Hashable, int] = {}
        # engine-context fields (mutated by the owning engine thread)
        self.step = 0
        self._reason: Optional[str] = None
        self._req: Optional[int] = None

    # -- engine context ----------------------------------------------------

    @contextlib.contextmanager
    def why(self, reason: str, req_id: Optional[int] = None):
        """Attribute every event recorded inside the block to
        ``reason`` (and optionally a request id). Re-entrant: the
        previous context is restored on exit."""
        prev = (self._reason, self._req)
        self._reason = reason
        self._req = req_id
        try:
            yield
        finally:
            self._reason, self._req = prev

    # -- recording ---------------------------------------------------------

    def record(self, event: str, owner: Hashable,
               pages: Sequence[int] = (), n: int = 0,
               new_owner: Hashable = None,
               rereserve: bool = False,
               reserved_freed: int = 0) -> None:
        """Append one event and update the live shadow. ``n`` carries
        counts for page-less events (reserve); ``reserved_freed`` is
        the reservation a ``free`` dropped alongside the pages."""
        self.seq += 1
        npages = len(pages)
        rec: Dict[str, Any] = {
            "seq": self.seq,
            "t_us": time.monotonic() * 1e6,
            "ev": event,
            "owner": _fmt_owner(owner),
            "pages": [int(p) for p in pages],
            "step": self.step,
        }
        if n:
            rec["n"] = int(n)
        if new_owner is not None:
            rec["to"] = _fmt_owner(new_owner)
        # reservation side-effects travel IN the event too (not just
        # the in-memory shadow): a ring-tail consumer must be able to
        # tell a rollback-release from a final release and reconstruct
        # reservation state from the events alone
        if rereserve:
            rec["rereserve"] = True
        if reserved_freed:
            rec["reserved_freed"] = int(reserved_freed)
        if self._reason is not None:
            rec["reason"] = self._reason
        if self._req is not None:
            rec["req"] = self._req
        if len(self.ring) == self.capacity:
            self.dropped_total += 1
        self.ring.append(rec)
        self.events_by_kind[event] = \
            self.events_by_kind.get(event, 0) + 1
        for p in rec["pages"]:
            h = self._page_hist.get(p)
            if h is None:
                h = self._page_hist[p] = collections.deque(
                    maxlen=self._page_history)
            h.append(rec)
        # live shadow (spill/restore/splice are device-IO annotations,
        # not ownership moves — they don't touch the shadow)
        if event == "alloc":
            self._bump(self._live, owner, npages)
        elif event == "reserve":
            self._bump(self._reserved, owner, int(n))
        elif event == "alloc_reserved":
            self._bump(self._live, owner, npages)
            self._bump(self._reserved, owner, -npages)
        elif event == "release":
            self._bump(self._live, owner, -npages)
            if rereserve:
                self._bump(self._reserved, owner, npages)
        elif event == "free":
            self._live.pop(owner, None)
            self._reserved.pop(owner, None)
        elif event == "transfer":
            self._bump(self._live, owner, -npages)
            self._bump(self._live, new_owner, npages)

    @staticmethod
    def _bump(d: Dict[Hashable, int], owner: Hashable, n: int) -> None:
        v = d.get(owner, 0) + n
        if v:
            d[owner] = v
        else:
            d.pop(owner, None)

    # -- read surfaces -----------------------------------------------------

    def tail(self, n: int = 256) -> List[Dict[str, Any]]:
        """The most recent ``n`` events, oldest first (JSON-safe —
        what the flight bundle and the ``capacity`` op carry). Conn
        threads read this while the engine appends; retry the benign
        mutation-during-copy race (the health-op discipline)."""
        if n <= 0:
            return []
        for _ in range(3):
            try:
                return list(self.ring)[-n:]
            except RuntimeError:
                continue
        return []

    def history(self, page: int) -> List[Dict[str, Any]]:
        """The retained event history of one page, oldest first."""
        h = self._page_hist.get(int(page))
        return list(h) if h is not None else []

    def history_for_owner(self, owner: Hashable
                          ) -> List[Dict[str, Any]]:
        """Ring events that name ``owner`` (as owner, target, or
        request context), oldest first — the stall/deadline unwind
        dump's source."""
        key = _fmt_owner(owner)
        return [r for r in self.ring
                if r.get("owner") == key or r.get("to") == key
                or r.get("req") == key]

    def stats(self) -> Dict[str, Any]:
        for _ in range(3):  # scrape-thread reads vs engine appends
            try:
                by_kind = dict(self.events_by_kind)
                break
            except RuntimeError:
                by_kind = {}
        return {"events_total": self.seq,
                "ring": len(self.ring),
                "capacity": self.capacity,
                "dropped_total": self.dropped_total,
                "by_kind": by_kind,
                "live_owners": len(self._live),
                "reserved_owners": len(self._reserved)}

    # -- forensics ---------------------------------------------------------

    def forensics(self, owned: Dict[Hashable, Sequence[int]],
                  reserved: Dict[Hashable, int],
                  max_pages: int = 16) -> str:
        """Human-readable ownership history for dangling pages — what
        ``check_no_leak`` appends to its failure so a leak names the
        owner chain and last event instead of a count."""
        lines: List[str] = []
        shown = 0
        for owner, pages in owned.items():
            for p in pages:
                if shown >= max_pages:
                    lines.append(f"  ... ({sum(map(len, owned.values())) - shown} more pages)")
                    return "\n".join(lines)
                shown += 1
                hist = self.history(p)
                if hist:
                    chain = " -> ".join(
                        f"#{r['seq']} step {r['step']} {r['ev']} "
                        f"owner={r['owner']!r}"
                        + (f"->{r['to']!r}" if "to" in r else "")
                        + (f" ({r['reason']})" if "reason" in r else "")
                        for r in hist)
                else:
                    chain = "(no retained events)"
                lines.append(f"  page {int(p)} owned by "
                             f"{_fmt_owner(owner)!r}: {chain}")
        for owner, n in reserved.items():
            lines.append(f"  reservation of {n} page(s) held by "
                         f"{_fmt_owner(owner)!r}")
        return "\n".join(lines)

    # -- reconciliation (chaos invariant 5) --------------------------------

    def reconcile(self, allocator=None) -> Dict[str, Any]:
        """Cross-check the event-derived live shadow against the
        allocator's actual books: every alloc/reserve must have been
        matched by a release/free (drained engines), and the shadow's
        surviving owners (e.g. prefix-cache chains) must agree with
        the allocator exactly. A mismatch means pages moved outside
        the recorded event stream — the bug class counters can't
        localize."""
        live = {k: v for k, v in self._live.items() if v}
        res = {k: v for k, v in self._reserved.items() if v}
        out: Dict[str, Any] = {"enabled": True,
                               "events_total": self.seq,
                               "dropped_total": self.dropped_total,
                               "live_owners": len(live),
                               "reserved_owners": len(res)}
        mismatches: List[str] = []
        if allocator is not None:
            actual = {o: len(p) for o, p in
                      allocator.owners().items()}
            for o in set(live) | set(actual):
                if live.get(o, 0) != actual.get(o, 0):
                    mismatches.append(
                        f"owner {_fmt_owner(o)!r}: ledger "
                        f"{live.get(o, 0)} != allocator "
                        f"{actual.get(o, 0)} pages")
            act_res = {o: n for o, n in
                       getattr(allocator, "_reserved", {}).items() if n}
            for o in set(res) | set(act_res):
                if res.get(o, 0) != act_res.get(o, 0):
                    mismatches.append(
                        f"owner {_fmt_owner(o)!r}: ledger reservation "
                        f"{res.get(o, 0)} != allocator "
                        f"{act_res.get(o, 0)}")
        out["ok"] = not mismatches
        if mismatches:
            out["mismatches"] = mismatches[:16]
        return out


def forecast_exhaustion(entries: Sequence[Dict[str, Any]],
                        alpha: float = 0.3) -> Dict[str, Any]:
    """EWMA time-to-exhaustion forecast over step-timeline ring
    deltas: consecutive entries' ``free_pages`` drops per wall second
    are EWMA-smoothed into a consumption rate; positive rate projects
    ``free / rate`` seconds to an empty free list. Negative/zero net
    rate (freeing or steady) forecasts no exhaustion (``tte_s`` None).
    Pure host math over numbers the ring already records — unit-tested
    against synthetic entries (tests/test_memory_observer.py)."""
    ewma: Optional[float] = None
    prev_t = prev_free = None
    samples = 0
    for e in entries:
        f, t = e.get("free_pages"), e.get("t_us")
        if f is None or t is None:
            continue
        if prev_t is not None:
            dt = (t - prev_t) / 1e6
            if dt > 0:
                rate = (prev_free - f) / dt  # pages consumed per s
                ewma = (rate if ewma is None
                        else (1.0 - alpha) * ewma + alpha * rate)
                samples += 1
        prev_t, prev_free = t, f
    out: Dict[str, Any] = {"samples": samples,
                           "free_pages": prev_free,
                           "rate_pages_per_s": None, "tte_s": None}
    if ewma is not None:
        out["rate_pages_per_s"] = round(float(ewma), 6)
        if ewma > 1e-9 and prev_free is not None:
            out["tte_s"] = round(float(prev_free) / float(ewma), 3)
    return out
