"""AOT inference predictor."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


class PrecisionType:
    Float32 = "float32"
    Bfloat16 = "bfloat16"
    Half = "float16"
    Int8 = "int8"


class Config:
    """reference: AnalysisConfig (inference/api/paddle_analysis_config.h).
    Holds model path + device/precision knobs; graph optimization choices
    map to XLA options."""

    def __init__(self, model_path_prefix: Optional[str] = None):
        self.model_path_prefix = model_path_prefix
        self._device = "auto"
        self._precision = PrecisionType.Float32
        self._enable_profile = False
        self._memory_optim = True

    def set_model(self, path_prefix: str) -> None:
        self.model_path_prefix = path_prefix

    def enable_tpu(self) -> None:
        self._device = "tpu"

    def disable_gpu(self) -> None:
        self._device = "cpu"

    def set_cpu_math_library_num_threads(self, n: int) -> None:
        pass

    def enable_memory_optim(self, flag: bool = True) -> None:
        self._memory_optim = flag

    def enable_profile(self) -> None:
        self._enable_profile = True

    def set_precision(self, precision: str) -> None:
        self._precision = precision

    def enable_low_precision(self, precision: str = PrecisionType.Int8
                             ) -> None:
        """Serve in low precision. bf16/f16: params are cast (HBM
        footprint/bandwidth win). int8: the model must have been
        PTQ-converted (quantization.convert_to_int8) before export — the
        saved program already contains the int8 dot/conv kernels, so no
        param cast is applied at load."""
        self._precision = precision

    # reference naming: enable_tensorrt_engine configures the fused
    # low-precision path; here it just selects precision.
    def enable_tensorrt_engine(self, workspace_size=0, max_batch_size=1,
                               min_subgraph_size=3,
                               precision_mode=PrecisionType.Float32,
                               use_static=False, use_calib_mode=False):
        self._precision = precision_mode


class Tensor:
    """Zero-copy handle (reference: paddle_tensor.h ZeroCopyTensor)."""

    def __init__(self, name: str, owner: "Predictor", is_input: bool):
        self.name = name
        self._owner = owner
        self._is_input = is_input

    def reshape(self, shape) -> None:
        pass  # shapes are taken from the bound array

    def copy_from_cpu(self, arr: np.ndarray) -> None:
        self._owner._inputs[self.name] = jnp.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._owner._outputs[self.name])

    def share_external_data(self, arr) -> None:
        self._owner._inputs[self.name] = arr


class Predictor:
    """reference: AnalysisPredictor. Loads the exported StableHLO program
    and AOT-compiles it once; run() is a single device launch."""

    def __init__(self, config: Config):
        from ..static.program import LoadedProgram

        self.config = config
        # low-precision serving: params held in bf16/f16 (HBM footprint/
        # bandwidth win), cast back to the artifact signature inside the
        # jitted call where XLA fuses the casts
        self._program = LoadedProgram(config.model_path_prefix,
                                      precision=config._precision)
        self._input_names = [
            s.name or f"x{i}"
            for i, s in enumerate(self._program.input_specs)]
        self._inputs: Dict[str, Any] = {}
        self._outputs: Dict[str, Any] = {}
        self._output_names: List[str] = []

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> Tensor:
        return Tensor(name, self, True)

    def get_output_names(self) -> List[str]:
        return list(self._output_names) or ["out0"]

    def get_output_handle(self, name: str) -> Tensor:
        return Tensor(name, self, False)

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        if inputs is not None:
            for n, a in zip(self._input_names, inputs):
                self._inputs[n] = jnp.asarray(a)
        args = [self._inputs[n] for n in self._input_names]
        out = self._program.run(*args)
        leaves = jax.tree_util.tree_leaves(out)
        self._output_names = [f"out{i}" for i in range(len(leaves))]
        self._outputs = dict(zip(self._output_names, leaves))
        if inputs is not None:
            return [np.asarray(l) for l in leaves]
        return None

    def try_shrink_memory(self) -> None:
        pass


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


class DataType:
    """reference: paddle/fluid/inference/api/paddle_api.h PaddleDType —
    dtype tags on the inference tensor ABI."""
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6  # beyond reference: first-class on TPU


class PlaceType:
    """reference: paddle_tensor.h PlaceType."""
    UNK = -1
    CPU = 0
    GPU = 1
    XPU = 2
    NPU = 3
    TPU = 4


def get_num_bytes_of_data_type(dtype) -> int:
    """reference: paddle.inference.get_num_bytes_of_data_type."""
    sizes = {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
             DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
             DataType.BFLOAT16: 2}
    if dtype in sizes:
        return sizes[dtype]
    import numpy as np
    return int(np.dtype(dtype).itemsize)


def get_version() -> str:
    """reference: paddle.inference.get_version."""
    import paddle_tpu
    return f"paddle_tpu inference {paddle_tpu.__version__}"


class PredictorPool:
    """reference: paddle.inference.PredictorPool (capi predictor pool) —
    N predictors over one config. On TPU the compiled program is shared
    (the jit cache keys on the artifact), so the pool is N lightweight
    handles for thread-confined use."""

    def __init__(self, config: Config, size: int = 1):
        self._predictors = [create_predictor(config)
                            for _ in range(max(1, int(size)))]

    def retrieve(self, idx: int) -> Predictor:
        return self._predictors[idx % len(self._predictors)]

    def __len__(self) -> int:
        return len(self._predictors)
