"""Continuous-batching decode engine over the paged KV cache.

The serving-side half of the paged decode subsystem (the kernel half is
`ops/pallas/paged_attention.py`, the model half `models/gpt.py`
PagedKVCache): a fixed-slot decode batch that admits and evicts
sequences MID-FLIGHT, recycling completed sequences' KV pages to newly
admitted ones. This is what the paging buys beyond ragged bandwidth —
the dense StaticKVCache path must run every co-batched request for the
longest request's duration (or re-prefill), while here a finished slot
is refilled on the next step without touching the other slots' compiled
program.

Design (TPU-native fixed shapes; paper basis: *Ragged Paged Attention*,
PAPERS.md — the same pool/page-table layout its kernel consumes):

- DEVICE state is fully static-shaped: per-layer page pools, one
  ``page_table [num_slots, max_pages]``, ``seq_lens [num_slots]``, and
  the per-slot current token. ONE compiled decode step serves the
  engine's whole lifetime; prefill compiles once per prompt bucket.
- HOST state is the scheduler: a free-list `PageAllocator`, the wait
  queue, and per-slot request bookkeeping. Admission allocates
  ceil(capacity/page) pages and runs a bucket-padded prefill whose
  right padding is redirected to the pool's reserved scratch page
  (models/gpt.py paged_kv_append valid_len), so padded prompts never
  touch real pages; eviction returns the pages to the free list and
  parks the slot on the scratch page at length 0 (an empty slot
  attends nothing and produces defined zeros — see
  paged_attention_reference), so a freed page can be handed to the
  next request without any cross-slot read hazard.
- Inactive slots still ride through the fixed-shape decode step (their
  writes land on the scratch page and their lengths are reset on the
  host); that is the fixed-slot contract that keeps the hot loop at
  one compiled program.

Reference analog: the inference engine's multi-stream serving loop
(`inference/api/analysis_predictor.cc` + TensorRT's enqueue batching),
rebuilt as a scheduler over one jitted step instead of a stream pool.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["PageAllocator", "DecodeRequest", "ContinuousBatchingEngine",
           "create_decode_engine"]


class PageAllocator:
    """Host-side free-list allocator over the shared page pool.

    Pages are plain ints in [0, num_pages); the pool's reserved scratch
    page (index num_pages in the device arrays) is never handed out.
    `alloc` is all-or-nothing so a request that does not fit leaves the
    free list untouched (no partial reservations to unwind)."""

    def __init__(self, num_pages: int):
        self.num_pages = num_pages
        self._free: List[int] = list(range(num_pages))
        self._owned: Dict[int, List[int]] = {}

    @property
    def free_count(self) -> int:
        return len(self._free)

    def alloc(self, owner: int, n: int) -> Optional[List[int]]:
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        self._owned.setdefault(owner, []).extend(pages)
        return pages

    def free(self, owner: int) -> int:
        pages = self._owned.pop(owner, [])
        for p in pages:
            if p in self._free:  # double free = scheduler bug
                raise RuntimeError(f"page {p} double-freed")
        self._free.extend(pages)
        return len(pages)

    def check_no_leak(self) -> None:
        if self._owned or len(self._free) != self.num_pages:
            raise RuntimeError(
                f"page leak: {sum(map(len, self._owned.values()))} owned "
                f"by {sorted(self._owned)} with "
                f"{len(self._free)}/{self.num_pages} free")


@dataclasses.dataclass
class DecodeRequest:
    """One generation request in the engine."""
    req_id: int
    prompt: np.ndarray                # [len] int32
    max_new_tokens: int
    eos_token: Optional[int] = None
    generated: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    done: bool = False

    @property
    def tokens(self) -> np.ndarray:
        return np.concatenate([self.prompt,
                               np.asarray(self.generated, np.int32)])


class ContinuousBatchingEngine:
    """Fixed-slot continuous batching over one jitted paged decode step.

    ``num_pages`` sizes the shared pool; with
    num_pages < num_slots * max_pages_per_seq the engine oversubscribes
    slots against real memory and admission blocks on the free list —
    the page-recycling regime the tests pin. Greedy decoding (the
    deterministic serving mode; sampling belongs to generate())."""

    def __init__(self, model, num_slots: int = 4, page_size: int = 64,
                 max_seq_len: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 kv_int8: bool = False,
                 prompt_buckets: Sequence[int] = ()):
        import jax.numpy as jnp

        from ..nn.layer import functional_state
        from ..models.gpt import paged_cache_create

        self.model = model
        model.eval()
        cfg = model.config
        self.cfg = cfg
        self.page_size = int(page_size)
        self.num_slots = int(num_slots)
        self.max_seq_len = int(max_seq_len or cfg.max_seq_len)
        self.max_pages = -(-self.max_seq_len // self.page_size)
        self.num_pages = int(num_pages if num_pages is not None
                             else num_slots * self.max_pages)
        self.kv_int8 = bool(kv_int8)
        if not prompt_buckets:
            bucket, prompt_buckets = self.page_size, []
            while bucket < self.max_seq_len:
                prompt_buckets.append(bucket)
                bucket *= 2
            prompt_buckets.append(self.max_seq_len)
        self.prompt_buckets = sorted(set(int(x) for x in prompt_buckets))

        self.allocator = PageAllocator(self.num_pages)
        self._scratch = self.num_pages  # reserved page index
        dt = functional_state(model)["params"]["gpt.wte.weight"].dtype
        nh, hd, nl = cfg.num_heads, cfg.head_dim, cfg.num_layers
        self._nl = nl
        # one DISTINCT pool per layer (not nl references to one array:
        # the jitted step donates the pool buffers, and donating the
        # same buffer for two arguments is an error)
        protos = [paged_cache_create(
            1, self.num_pages, self.page_size, nh, hd, dt,
            self.max_pages, quantized=self.kv_int8) for _ in range(nl)]
        self._pools = {
            "k": [p.k_pages for p in protos],
            "v": [p.v_pages for p in protos],
            "ks": [p.k_scale for p in protos],
            "vs": [p.v_scale for p in protos],
        }
        # host-owned scheduler state
        self._table = np.full((self.num_slots, self.max_pages),
                              self._scratch, np.int32)
        self._lens = np.zeros((self.num_slots,), np.int32)
        self._cur = np.zeros((self.num_slots,), np.int32)
        self._slots: List[Optional[DecodeRequest]] = \
            [None] * self.num_slots
        self._queue: List[DecodeRequest] = []
        self._finished: Dict[int, DecodeRequest] = {}
        self._next_id = 0
        self._jnp = jnp
        self._decode_jit = None
        self._prefill_jit = None
        self._state_cache = None
        self.steps = 0

    # -- request lifecycle -------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               eos_token: Optional[int] = None) -> int:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new {max_new_tokens} exceeds "
                f"max_seq_len {self.max_seq_len}")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1 (the prefill "
                             "itself produces the first token)")
        if len(prompt) > self.prompt_buckets[-1]:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the largest "
                f"prompt bucket {self.prompt_buckets[-1]}")
        need = -(-(len(prompt) + max_new_tokens) // self.page_size)
        if need > self.num_pages:
            # would block the FIFO head forever — no amount of
            # recycling frees pages that never existed
            raise ValueError(
                f"request needs {need} pages but the pool has only "
                f"{self.num_pages}; raise num_pages or shrink the "
                f"request")
        req = DecodeRequest(self._next_id, prompt, int(max_new_tokens),
                            eos_token)
        self._next_id += 1
        self._queue.append(req)
        return req.req_id

    def result(self, req_id: int, pop: bool = False
               ) -> Optional[np.ndarray]:
        req = (self._finished.pop(req_id, None) if pop
               else self._finished.get(req_id))
        return None if req is None else req.tokens

    @property
    def num_active(self) -> int:
        return sum(r is not None for r in self._slots)

    # -- jitted device programs -------------------------------------------

    def _caches(self, pools, table, lens):
        from ..models.gpt import PagedKVCache
        return [PagedKVCache(pools["k"][i], pools["v"][i],
                             pools["ks"][i], pools["vs"][i],
                             table, lens) for i in range(self._nl)]

    def _fresh_state(self, refresh: bool = False):
        """Model functional state (params AND buffers — converted
        layers hold int8 weights as buffers) for the jitted calls.
        Re-read at every ADMISSION (refresh=True) so post-construction
        weight mutation (set_state_dict, convert_to_weight_only_int8)
        is served, not silently ignored — a structural change simply
        retraces via the new argument pytree (the r5 stale-cache
        lesson). The per-token decode step reuses the cached dict:
        rebuilding hundreds of entries per generated token is pure
        host overhead on the hot path."""
        if refresh or self._state_cache is None:
            from ..nn.layer import functional_state
            self._state_cache = functional_state(self.model)
        return self._state_cache

    def _build_decode(self):
        import jax

        from ..autograd.engine import no_grad
        from ..nn.layer import bind_state
        from ..tensor import Tensor

        def raw(t):
            return t.value if isinstance(t, Tensor) else t

        def step(state, pools, table, lens, tokens):
            caches = self._caches(pools, table, lens)
            with bind_state(self.model, state), no_grad():
                logits, nc = self.model.forward(Tensor(tokens[:, None]),
                                                caches=caches)
            nxt = self._jnp.argmax(raw(logits)[:, -1], -1).astype(
                self._jnp.int32)
            new_pools = {
                "k": [raw(c.k_pages) for c in nc],
                "v": [raw(c.v_pages) for c in nc],
                "ks": [raw(c.k_scale) if self.kv_int8 else None
                       for c in nc],
                "vs": [raw(c.v_scale) if self.kv_int8 else None
                       for c in nc],
            }
            return nxt, new_pools, raw(nc[0].seq_lens)

        # donate the pools: the append scatters then update the pool
        # buffers IN PLACE instead of materializing a fresh copy of
        # every per-layer pool each token (~GBs/step at serving scale,
        # plus 2x peak KV memory); the engine always adopts the
        # returned pools, so the donated buffers are never reused.
        # (On CPU donation is ignored with a warning — harmless.)
        return jax.jit(step, donate_argnums=(1,))

    def _build_prefill(self):
        """One jitted prefill; jax.jit's shape-keyed cache compiles it
        once per prompt bucket (the bucket IS the ids shape)."""
        import jax

        from ..autograd.engine import no_grad
        from ..nn.layer import bind_state
        from ..tensor import Tensor

        def raw(t):
            return t.value if isinstance(t, Tensor) else t

        def prefill(state, pools, trow, plen, ids):
            caches = self._caches(
                pools, trow, self._jnp.zeros((1,), self._jnp.int32))
            with bind_state(self.model, state), no_grad():
                logits, nc = self.model.forward(Tensor(ids), caches=caches,
                                                prefill_lens=plen)
            nxt = self._jnp.argmax(
                raw(logits)[0, plen[0] - 1], -1).astype(self._jnp.int32)
            new_pools = {
                "k": [raw(c.k_pages) for c in nc],
                "v": [raw(c.v_pages) for c in nc],
                "ks": [raw(c.k_scale) if self.kv_int8 else None
                       for c in nc],
                "vs": [raw(c.v_scale) if self.kv_int8 else None
                       for c in nc],
            }
            return nxt, new_pools

        return jax.jit(prefill, donate_argnums=(1,))

    # -- scheduler ---------------------------------------------------------

    def _bucket(self, n: int) -> int:
        for b in self.prompt_buckets:
            if n <= b:
                return b
        return self.prompt_buckets[-1]

    def _admit(self) -> None:
        jnp = self._jnp
        for slot in range(self.num_slots):
            if not self._queue or self._slots[slot] is not None:
                continue
            req = self._queue[0]
            capacity = len(req.prompt) + req.max_new_tokens
            need = -(-capacity // self.page_size)
            pages = self.allocator.alloc(req.req_id, need)
            if pages is None:
                break  # FIFO: don't starve the head request
            self._queue.pop(0)
            row = np.full((self.max_pages,), self._scratch, np.int32)
            row[:need] = pages
            self._table[slot] = row
            bucket = self._bucket(len(req.prompt))
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :len(req.prompt)] = req.prompt
            if self._prefill_jit is None:
                self._prefill_jit = self._build_prefill()
            try:
                nxt, pools = self._prefill_jit(
                    self._fresh_state(refresh=True), self._pools,
                    jnp.asarray(row[None]),
                    jnp.asarray([len(req.prompt)], jnp.int32),
                    jnp.asarray(ids))
            except Exception:
                # unwind the half-applied admission so a prefill
                # failure (e.g. a remote-compile transport error on a
                # new prompt bucket) is retryable instead of losing
                # the request and leaking its pages: free the pages,
                # park the slot, put the request back at the queue
                # head, then surface the error. (If the failure hit
                # AFTER execution began, the donated pool buffers may
                # be gone with it — compile-time failures, the
                # documented class, leave them untouched.)
                self.allocator.free(req.req_id)
                self._table[slot] = self._scratch
                self._queue.insert(0, req)
                raise
            self._pools = pools
            self._lens[slot] = len(req.prompt)
            self._cur[slot] = int(nxt)
            req.slot = slot
            req.generated.append(int(nxt))
            self._slots[slot] = req
            self._maybe_finish(slot)

    def _maybe_finish(self, slot: int) -> None:
        req = self._slots[slot]
        if req is None:
            return
        hit_eos = (req.eos_token is not None and req.generated and
                   req.generated[-1] == req.eos_token)
        if len(req.generated) >= req.max_new_tokens or hit_eos:
            req.done = True
            self._finished[req.req_id] = req
            self.allocator.free(req.req_id)
            self._table[slot] = self._scratch  # park on scratch page
            self._lens[slot] = 0
            self._cur[slot] = 0
            self._slots[slot] = None

    def step(self) -> int:
        """Admit what fits, run ONE fixed-shape decode step, evict what
        finished. Returns the number of still-active slots."""
        jnp = self._jnp
        self._admit()
        if self.num_active == 0:
            return 0
        if self._decode_jit is None:
            self._decode_jit = self._build_decode()
        active = np.array([r is not None for r in self._slots])
        nxt, pools, lens_new = self._decode_jit(
            self._fresh_state(), self._pools,
            jnp.asarray(self._table), jnp.asarray(self._lens),
            jnp.asarray(self._cur))
        self._pools = pools
        nxt = np.asarray(nxt)
        # inactive slots wrote to the scratch page; pin their length
        # back to 0 (empty = attends nothing, defined zeros)
        self._lens = np.where(active, np.asarray(lens_new), 0).astype(
            np.int32)
        self.steps += 1
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            req.generated.append(int(nxt[slot]))
            self._cur[slot] = int(nxt[slot])
            self._maybe_finish(slot)
        return self.num_active

    def run(self, max_steps: int = 100000) -> Dict[int, np.ndarray]:
        """Drive until queue and slots drain; returns {req_id: tokens}
        for everything finished so far and DRAINS the finished store
        (a long-running engine must not accumulate past results —
        callers polling step() themselves use result(id, pop=True))."""
        steps = 0
        while self._queue or self.num_active:
            before = (len(self._queue), self.num_active)
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(f"engine did not drain in {max_steps} "
                                   f"steps (state {before})")
        self.allocator.check_no_leak()
        out = {rid: req.tokens for rid, req in self._finished.items()}
        self._finished.clear()
        return out


def create_decode_engine(model, **kwargs) -> ContinuousBatchingEngine:
    """Serving-path entry (mirrors inference.create_predictor): build a
    continuous-batching decode engine over a causal-LM layer."""
    return ContinuousBatchingEngine(model, **kwargs)
